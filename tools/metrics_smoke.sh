#!/bin/bash
# Telemetry smoke (ISSUE 3 acceptance, operator-runnable): boot the
# REAL `python -m znicz_tpu serve` CLI on a free port, fire N predicts
# (some deliberately malformed), then assert the scrape contract:
#   * GET /metrics with Accept: text/plain parses as Prometheus text
#     exposition v0.0.4 and includes predict_latency_ms buckets and
#     breaker_state;
#   * requests_total / errors_total match exactly what was sent;
#   * the JSON and text views report identical counter values;
#   * every POST /predict response carries an X-Request-Id, echoing
#     the client's when supplied;
#   * the JSON view carries a `rev` build stamp;
#   * compile accounting (telemetry.compilestats): --warmup-shape
#     precompiles every bucket as cause=cold, the predict burst adds
#     ZERO request-path compiles (no new_bucket/fallback samples),
#     the hot reload's canary compile records cause=reload, and the
#     executable cache hit/miss counters match the traffic.
#
# Registered beside tools/chaos_smoke.sh; pytest wrapper (marked slow):
# tests/test_metrics_smoke.py.
#
# Usage:  bash tools/metrics_smoke.sh [n_good] [n_bad]
set -u -o pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python - "${1:-6}" "${2:-3}" <<'PY'
import json, os, re, signal, subprocess, sys, tempfile, time
import urllib.error, urllib.request

n_good, n_bad = int(sys.argv[1]), int(sys.argv[2])
fails = []


def check(cond, msg):
    print(("ok  " if cond else "FAIL") + " " + msg)
    if not cond:
        fails.append(msg)


def parse_exposition(text):
    """Minimal v0.0.4 parser: {series-with-labels: float}; raises on a
    malformed line, which is the point — a scraper would too."""
    series, typed = {}, {}
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            typed[name] = kind
            continue
        if line.startswith("#"):
            continue
        m = re.fullmatch(
            r'([a-zA-Z_:][a-zA-Z0-9_:]*)'
            r'(\{(?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*",?)*\})? '
            r'([0-9.eE+-]+|\+Inf|-Inf|NaN)', line)
        if m is None:
            raise ValueError(f"unparseable exposition line: {line!r}")
        series[m.group(1) + (m.group(2) or "")] = float(
            m.group(3).replace("+Inf", "inf").replace("-Inf", "-inf"))
    return series, typed


with tempfile.TemporaryDirectory(prefix="znicz_metrics_smoke_") as tmp:
    model = os.path.join(tmp, "demo.znn")
    from znicz_tpu.resilience.chaos import _write_demo_znn
    _write_demo_znn(model)
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [sys.executable, "-m", "znicz_tpu", "serve", "--model", model,
         "--port", str(port), "--max-wait-ms", "1",
         "--warmup-shape", "4"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    url = f"http://127.0.0.1:{port}/"
    try:
        for _ in range(120):                    # wait for the listener
            try:
                urllib.request.urlopen(url + "healthz", timeout=2)
                break
            except Exception:
                if proc.poll() is not None:
                    out = proc.stdout.read().decode(errors="replace")
                    sys.exit(f"serve exited rc={proc.returncode}:\n"
                             + out[-2000:])
                time.sleep(0.5)
        else:
            sys.exit("serve never answered /healthz")

        rids = []
        for i in range(n_good):
            req = urllib.request.Request(
                url + "predict",
                json.dumps({"inputs": [[0.1, -0.2, 0.3, 0.4]]}).encode(),
                {"Content-Type": "application/json",
                 "X-Request-Id": f"smoke-{i}"})
            with urllib.request.urlopen(req, timeout=30) as r:
                check(r.status == 200, f"good predict {i} -> 200")
                rids.append(r.headers.get("X-Request-Id"))
        check(rids == [f"smoke-{i}" for i in range(n_good)],
              "client X-Request-Id echoed on every 200")
        bad_codes = []
        for i in range(n_bad):                  # raw non-JSON body
            req = urllib.request.Request(
                url + "predict", b"this is not json",
                {"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req, timeout=30)
                bad_codes.append(200)
            except urllib.error.HTTPError as e:
                bad_codes.append(e.code)
                check(e.headers.get("X-Request-Id") is not None,
                      f"malformed predict {i} still carries a "
                      f"generated X-Request-Id")
        check(bad_codes == [400] * n_bad, f"malformed -> 400 {bad_codes}")

        with urllib.request.urlopen(url + "metrics", timeout=10) as r:
            m = json.loads(r.read())
        req = urllib.request.Request(url + "metrics",
                                     headers={"Accept": "text/plain"})
        with urllib.request.urlopen(req, timeout=10) as r:
            check("version=0.0.4" in r.headers.get("Content-Type", ""),
                  "text view Content-Type is v0.0.4")
            text = r.read().decode()
        series, typed = parse_exposition(text)   # raises if malformed
        check(typed.get("predict_latency_ms") == "histogram",
              "predict_latency_ms typed histogram")
        check(any(k.startswith("predict_latency_ms_bucket") for k in
                  series), "predict_latency_ms buckets present")
        check(series.get('breaker_state{state="closed"}') == 1.0,
              "breaker_state enum present (closed)")
        # overload-defense families (znicz_tpu.resilience.overload):
        # registered at import, scraped from zero on an idle replica
        # so dashboards see the series before the first incident
        for fam, kind in (("deadline_exceeded_total", "counter"),
                          ("retry_budget_tokens", "gauge"),
                          ("hedges_total", "counter"),
                          ("shed_total", "counter"),
                          ("drain_state", "gauge")):
            check(typed.get(fam) == kind, f"{fam} typed {kind}")
        check(series.get("drain_state") == 0.0,
              "drain_state == 0 (serving) on a live replica")
        sent = n_good + n_bad
        got_pred = sum(v for k, v in series.items()
                       if k.startswith('requests_total{')
                       and 'route="/predict"' in k)
        got_err = sum(v for k, v in series.items()
                      if k.startswith('errors_total{')
                      and 'route="/predict"' in k)
        check(got_pred == sent,
              f"text requests_total/predict == {sent} (got {got_pred})")
        check(got_err == n_bad,
              f"text errors_total/predict == {n_bad} (got {got_err})")
        check(series.get("predict_latency_ms_count") == sent,
              "latency histogram count == requests sent")
        # JSON/text consistency: same Counter objects back both views.
        # Compare the /predict route (scrapes themselves only bump the
        # /metrics route, so these children are stable between views).
        jr = m["requests"]["requests_by_route_code"]
        check(jr.get("code=200,route=/predict") == n_good
              and jr.get("code=400,route=/predict") == n_bad,
              "JSON per-route requests == sent")
        check(m["requests"]["errors_by_route_code"]
              .get("code=400,route=/predict") == n_bad
              and got_err == n_bad,
              "JSON and text /predict error counters identical")
        check(series.get('requests_total{code="200",route="/predict"}')
              == jr.get("code=200,route=/predict"),
              "JSON and text /predict request counters identical")
        check(m["completed"] == series.get("serving_batcher_completed"),
              "JSON batcher completed == text serving_batcher_completed")
        check("rev" in m, "JSON /metrics carries a rev build stamp")

        # hot reload (znicz_tpu.durability): re-read the same artifact
        # in place, then assert the reload/integrity metrics joined
        # the scrape contract
        req = urllib.request.Request(
            url + "admin/reload", json.dumps({"wait": True}).encode(),
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            rec = json.loads(r.read())
        check((rec.get("last_reload") or {}).get("outcome") == "ok",
              "POST /admin/reload (wait) reloads in place")
        check(rec.get("model_generation") == 2,
              "healthz generation bumped to 2 after the reload")
        req = urllib.request.Request(url + "metrics",
                                     headers={"Accept": "text/plain"})
        with urllib.request.urlopen(req, timeout=10) as r:
            series, typed = parse_exposition(r.read().decode())
        check(series.get('model_reloads_total{outcome="ok"}') == 1.0,
              "model_reloads_total{outcome=ok} == 1")
        check(series.get("model_generation") == 2.0,
              "model_generation gauge == 2")
        check(series.get("serving_engine_generation") == 2.0,
              "serving_engine_generation mirror == 2")
        check(series.get("artifact_verify_failures_total") == 0.0,
              "artifact_verify_failures_total present (and clean)")
        check(series.get("artifacts_quarantined_total") == 0.0,
              "artifacts_quarantined_total present (and clean)")
        check(series.get("manifests_healed_total") is not None,
              "manifests_healed_total present")
        # promotion families (znicz_tpu.promotion): registered by the
        # serve CLI from process start so dashboards see the series
        # before any controller drives this replica — zero while idle
        check(series.get("promotions_total") == 0.0,
              "promotions_total family present (controller idle)")
        check(series.get("slo_breaches_total") == 0.0,
              "slo_breaches_total family present (controller idle)")
        check(series.get("promotion_generation") == 0.0,
              "promotion_generation gauge present (no promotion yet)")
        # compile accounting (telemetry.compilestats): --warmup-shape 4
        # precompiled all 4 default buckets off the request path, so
        # the whole predict burst must have added ZERO request-path
        # compiles, and the reload's canary compile records its own
        # cause — the steady-state contract, as metrics.  The reload
        # additionally re-warms the NEW generation from the traffic
        # shape census (PR 8): 4 buckets for the one observed shape,
        # minus the canary-seeded one = 3 more cold compiles, all off
        # the request path
        check(series.get('compiles_total{cause="cold",'
                         'site="serving.engine"}') == 7.0,
              "warmup (4) + post-reload census re-warm (3) compiled "
              "as cause=cold")
        check(not any('cause="new_bucket"' in k or 'cause="fallback"' in k
                      for k in series),
              "zero request-path compiles (no new_bucket/fallback "
              "samples)")
        check(series.get('compiles_total{cause="reload",'
                         'site="serving.canary"}') == 1.0,
              "reload canary compile recorded (cause=reload)")
        check(series.get('compile_time_ms_count{site="serving.engine"}')
              == 7.0,
              "compile_time_ms histogram counted the 7 off-path builds")
        check(series.get('executable_cache_misses_total'
                         '{site="serving.engine"}') == 7.0,
              "cache misses == warmup + census re-warm builds")
        check(series.get('executable_cache_hits_total'
                         '{site="serving.engine"}') == float(n_good),
              f"cache hits == {n_good} good predicts")
        # cost attribution + SLO families (telemetry.sloengine /
        # serving.zoo, ISSUE 12): registered at import so every
        # serving process scrapes them from zero — a single-model
        # replica carries the families (label-free, zero) even though
        # only explicit zoos populate the model-labeled children
        for fam, kind in (("model_device_ms_total", "counter"),
                          ("model_latency_ms", "histogram"),
                          ("slo_burn_rate", "gauge"),
                          ("slo_budget_remaining", "gauge"),
                          ("slo_alerts_total", "counter"),
                          ("engine_busy_ratio", "gauge")):
            check(typed.get(fam) == kind, f"{fam} typed {kind}")
        check(not any(k.startswith("model_device_ms_total{")
                      for k in series),
              "single-model surface grows no model-labeled "
              "device-ms children")
        busy = series.get("engine_busy_ratio")
        check(busy is not None and 0.0 <= busy <= 1.0,
              f"engine_busy_ratio in [0, 1] (got {busy})")
        check(series.get("serving_engine_device_ms_total", 0.0) > 0.0,
              "engine device-time accounting moved under traffic")
        # wire protocol + response memoization + int8 serving families
        # (ISSUE 13): registered at import — an un-memoized fp32 JSON
        # replica still scrapes them (zero where idle), and the JSON
        # burst above counted into the wire-format label
        for fam, kind in (("wire_requests_total", "counter"),
                          ("response_cache_hits_total", "counter"),
                          ("response_cache_misses_total", "counter"),
                          ("response_cache_bytes", "gauge"),
                          ("quantize_fallback_total", "counter")):
            check(typed.get(fam) == kind, f"{fam} typed {kind}")
        check(series.get('wire_requests_total{format="json"}')
              == float(n_good),
              f"wire_requests_total{{format=json}} == {n_good} "
              f"decoded payloads (malformed bodies never count)")
        check(series.get("response_cache_hits_total") == 0.0,
              "response-cache families scrape zero without --memoize")
        check(series.get("quantize_fallback_total") == 0.0,
              "quantize_fallback_total present (fp32 serving, zero)")
        # distributed-tracing families (telemetry.tracestore, ISSUE
        # 18): registered at import, so an UNtraced replica (serve
        # defaults to --trace-sample 0) still scrapes them from zero —
        # and grows no stage-labeled children until a trace assembles
        for fam, kind in (("trace_stage_ms", "histogram"),
                          ("traces_retained_total", "counter"),
                          ("traces_dropped_total", "counter"),
                          ("trace_exemplars_total", "counter")):
            check(typed.get(fam) == kind, f"{fam} typed {kind}")
        check(series.get("trace_stage_ms_count") == 0.0,
              "trace_stage_ms scrapes zero on an untraced replica")
        check(not any(k.startswith("trace_stage_ms_bucket{")
                      and "stage=" in k for k in series),
              "no stage-labeled trace children without tracing")
        check(series.get("traces_retained_total") == 0.0
              and series.get("traces_dropped_total") == 0.0,
              "trace store counters scrape zero while untraced")
        check(series.get("trace_exemplars_total") == 0.0,
              "trace_exemplars_total scrapes zero while untraced")
    finally:
        proc.send_signal(signal.SIGINT)
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()

    # control-plane + gray-demotion families (znicz_tpu.fleet, ISSUE
    # 17): registered when the ROUTER process imports, scraped from
    # zero on a router that has no state dir and has demoted nothing —
    # dashboards see the series before the first crash or gray backend
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        rport = s.getsockname()[1]
    with socket.socket() as s:                  # a dead backend is fine:
        s.bind(("127.0.0.1", 0))                # the families must exist
        bport = s.getsockname()[1]              # before any traffic
    router = subprocess.Popen(
        [sys.executable, "-m", "znicz_tpu", "route",
         "--port", str(rport),
         "--backend", f"http://127.0.0.1:{bport}/,name=b0",
         "--probe-interval-s", "30"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    rurl = f"http://127.0.0.1:{rport}/"
    try:
        for _ in range(120):
            try:
                urllib.request.urlopen(rurl + "healthz", timeout=2)
                break
            except Exception:
                if router.poll() is not None:
                    out = router.stdout.read().decode(errors="replace")
                    sys.exit(f"route exited rc={router.returncode}:\n"
                             + out[-2000:])
                time.sleep(0.5)
        else:
            sys.exit("route never answered /healthz")
        req = urllib.request.Request(rurl + "metrics",
                                     headers={"Accept": "text/plain"})
        with urllib.request.urlopen(req, timeout=10) as r:
            series, typed = parse_exposition(r.read().decode())
        for fam, kind in (
                ("controlplane_journal_records_total", "counter"),
                ("backend_adopted_total", "counter"),
                ("gray_demotions_total", "counter"),
                ("backend_predict_ewma_ms", "gauge"),
                ("controlplane_reconcile_state", "gauge")):
            check(typed.get(fam) == kind, f"{fam} typed {kind}")
        check(series.get("controlplane_journal_records_total") == 0.0,
              "journal counter scrapes zero without --state-dir")
        check(series.get("backend_adopted_total") == 0.0,
              "backend_adopted_total scrapes zero before any restart")
        check(series.get("gray_demotions_total") == 0.0,
              "gray_demotions_total scrapes zero on a healthy fleet")
        check(series.get("controlplane_reconcile_state") == 0.0,
              "reconcile state == 0 (no state dir attached)")
        check(series.get('backend_predict_ewma_ms{backend="b0"}')
              == 0.0,
              "backend_predict_ewma_ms carries a zero child per "
              "backend before any predict")
        # HA + crash-loop families (znicz_tpu.fleet.ha, ISSUE 20):
        # registered at import, so a standalone router with no lease
        # attached still scrapes them — role/epoch zero, no takeovers,
        # nothing fenced, no crash loops
        for fam, kind in (("fleet_role", "gauge"),
                          ("ha_epoch", "gauge"),
                          ("ha_lease_renewals_total", "counter"),
                          ("ha_takeovers_total", "counter"),
                          ("ha_demotions_total", "counter"),
                          ("ha_fenced_mutations_total", "counter"),
                          ("autoscaler_crash_loops_total", "counter")):
            check(typed.get(fam) == kind, f"{fam} typed {kind}")
        check(series.get("ha_takeovers_total") == 0.0
              and series.get("ha_demotions_total") == 0.0,
              "HA takeover/demotion counters scrape zero without a "
              "lease attached")
        check(series.get("ha_fenced_mutations_total") == 0.0,
              "ha_fenced_mutations_total scrapes zero (nothing fenced)")
        check(series.get("autoscaler_crash_loops_total") == 0.0,
              "autoscaler_crash_loops_total scrapes zero on a healthy "
              "boot path")
        # the router registers the same tracing families (its store
        # and assembler live here) — present before any traffic
        for fam, kind in (("trace_stage_ms", "histogram"),
                          ("traces_retained_total", "counter"),
                          ("trace_exemplars_total", "counter")):
            check(typed.get(fam) == kind,
                  f"router scrape: {fam} typed {kind}")
    finally:
        router.send_signal(signal.SIGTERM)
        try:
            router.wait(timeout=15)
        except subprocess.TimeoutExpired:
            router.kill()

print(json.dumps({"ok": not fails, "violations": fails}))
sys.exit(1 if fails else 0)
PY
