#!/bin/bash
# Placement + autoscaling smoke (ISSUE 16 acceptance,
# operator-runnable):
#
#   1. `python -m znicz_tpu chaos --scenario placement` — three REAL
#      multi-tenant `serve` processes (the demo zoo on each) behind a
#      REAL `route --placement 1` process: the map covers every
#      tenant, steady-state traffic routes INSIDE placement sets,
#      fleet resident bytes stay ≤ (1 + replication) × one zoo's
#      weight bytes (the hint push releases non-placed copies), and
#      SIGKILLing the hot tenant's owner mid-burst heals via
#      re-placement with zero raw 500s and zero hangs.
#
#   2. a real `python -m znicz_tpu route --autoscale` process: boots
#      its own `serve` floor, scales OUT on an induced burn (a
#      latency objective with a sub-microsecond threshold makes every
#      request "bad", so sustained traffic = sustained burn), scales
#      IN through the graceful drain once traffic stops, and SIGTERM
#      exits rc 0 with every managed backend drained.
#
# Registered beside tools/fleet_smoke.sh / tools/zoo_smoke.sh.
#
# Usage:  bash tools/placement_smoke.sh
set -u -o pipefail
cd "$(dirname "$0")/.."

echo "== phase 1: chaos --scenario placement =="
JAX_PLATFORMS=cpu python -m znicz_tpu chaos --scenario placement || exit 1

echo "== phase 2: a real route --autoscale process =="
exec env JAX_PLATFORMS=cpu python - <<'PY'
import json, signal, socket, subprocess, sys, tempfile, time
import urllib.error, urllib.request
import os

fails = []


def check(cond, msg):
    print(("ok  " if cond else "FAIL") + " " + msg)
    if not cond:
        fails.append(msg)


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def healthz(url):
    with urllib.request.urlopen(url + "healthz", timeout=5) as r:
        return json.loads(r.read())


with tempfile.TemporaryDirectory(prefix="znicz_place_smoke_") as tmp:
    from znicz_tpu.resilience.chaos import _write_demo_znn

    model = os.path.join(tmp, "m.znn")
    _write_demo_znn(model)
    rport = free_port()
    url = f"http://127.0.0.1:{rport}/"
    # latency objective, threshold 1e-4 ms: EVERY answered request is
    # "bad", so live traffic burns the whole budget — deterministic
    # scale-out; stopped traffic reads idle — deterministic scale-in
    router = subprocess.Popen(
        [sys.executable, "-m", "znicz_tpu", "route",
         "--port", str(rport), "--autoscale",
         "--min-backends", "1", "--max-backends", "2",
         "--autoscale-interval-s", "0.5",
         "--autoscale-objective", "latency",
         "--autoscale-threshold-ms", "0.0001",
         "--autoscale-target", "0.9",
         "--autoscale-min-events", "3",
         "--breach-windows", "2",
         "--idle-windows", "4", "--idle-rps", "0.5",
         "--autoscale-cooldown-s", "1.0",
         "--drain-timeout-s", "15", "--boot-timeout-s", "180",
         "--probe-interval-s", "0.3",
         "--serve-arg=--model", f"--serve-arg={model}",
         "--serve-arg=--max-wait-ms", "--serve-arg=1",
         "--serve-arg=--warmup-shape", "--serve-arg=4"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    h = None
    for _ in range(360):
        try:
            h = healthz(url)
            break
        except Exception:
            if router.poll() is not None:
                print(f"FAIL router exited rc={router.returncode}")
                print(router.stdout.read().decode(errors="replace")[-600:])
                sys.exit(1)
            time.sleep(0.5)
    check(h is not None, "route --autoscale answers /healthz")
    if h is None:
        router.kill()
        sys.exit(1)
    asz = h.get("autoscale") or {}
    check(asz.get("backends") == 1,
          f"boots the min floor (backends={asz.get('backends')})")
    check(asz.get("managed"),
          f"the floor is autoscaler-managed ({asz.get('managed')})")

    body = json.dumps({"inputs": [[0.1, -0.2, 0.3, 0.4]]}).encode()

    def post():
        req = urllib.request.Request(
            url + "predict", body, {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            r.read()
            return r.status

    check(post() == 200, "predict 200 through the autoscaled fleet")

    # induce the burn: sustained traffic, every request past the
    # threshold; poll until the fleet scales out (boots take seconds)
    scaled_out = False
    deadline = time.monotonic() + 180.0
    while time.monotonic() < deadline:
        for _ in range(25):
            try:
                post()
            except Exception:
                pass
        try:
            asz = healthz(url).get("autoscale") or {}
        except Exception:
            asz = {}
        if asz.get("scale_outs", 0) >= 1 and asz.get("backends") == 2:
            scaled_out = True
            break
    check(scaled_out,
          f"scale-out on sustained burn (autoscale={asz})")

    # stop traffic: idle windows accumulate, the booted backend is
    # retired through the graceful drain
    scaled_in = False
    deadline = time.monotonic() + 120.0
    while time.monotonic() < deadline:
        try:
            asz = healthz(url).get("autoscale") or {}
        except Exception:
            asz = {}
        if asz.get("scale_ins", 0) >= 1 and asz.get("backends") == 1:
            scaled_in = True
            break
        time.sleep(0.5)
    check(scaled_in,
          f"scale-in drain once traffic stops (autoscale={asz})")
    check(post() == 200, "predict still 200 after the scale-in")

    router.send_signal(signal.SIGTERM)
    try:
        rc = router.wait(timeout=60)
    except subprocess.TimeoutExpired:
        router.kill()
        rc = router.wait(timeout=10)
    check(rc == 0, f"router SIGTERM exit rc {rc} (managed floor drained)")

print()
if fails:
    print(f"placement smoke: {len(fails)} failure(s)")
    sys.exit(1)
print("placement smoke: all checks passed")
PY
