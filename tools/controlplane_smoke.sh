#!/bin/bash
# Crash-safe control-plane smoke (ISSUE 17 acceptance,
# operator-runnable):
#
#   1. `python -m znicz_tpu chaos --scenario controlplane` — a REAL
#      `route --autoscale --state-dir` process boots two managed
#      serve children, takes admin mutations (weight override +
#      placement pin), and is SIGKILLed mid-burst; the restart on the
#      same port + state dir restores the journaled decisions,
#      re-adopts both children in place (same pids, zero
#      double-boots), answers 503 + Retry-After while reconciling,
#      gray-demotes a healthz-green/predict-sick backend to ~zero
#      effective weight, and serves zero raw 500s throughout.
#
#   2. a direct router-SIGKILL → restart → re-adopt phase from the
#      CLI surface: boot, kill -9, restart, and assert by pid
#      accounting that the SAME child serve process is re-adopted —
#      no orphan, no double-boot — then that the journal-and-keep
#      SIGTERM default leaves the child running for a third restart.
#
# Registered beside tools/fleet_smoke.sh / tools/placement_smoke.sh.
#
# Usage:  bash tools/controlplane_smoke.sh
set -u -o pipefail
cd "$(dirname "$0")/.."

echo "== phase 1: chaos --scenario controlplane =="
JAX_PLATFORMS=cpu python -m znicz_tpu chaos --scenario controlplane || exit 1

echo "== phase 2: SIGKILL -> restart -> re-adopt, by pid accounting =="
exec env JAX_PLATFORMS=cpu python - <<'PY'
import json, os, signal, socket, subprocess, sys, tempfile, time
import urllib.request

fails = []


def check(cond, msg):
    print(("ok  " if cond else "FAIL") + " " + msg)
    if not cond:
        fails.append(msg)


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def healthz(url):
    with urllib.request.urlopen(url + "healthz", timeout=5) as r:
        return json.loads(r.read())


def journal(state_dir):
    out = []
    try:
        with open(os.path.join(state_dir, "controlplane.jsonl")) as fh:
            for line in fh:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    pass
    except FileNotFoundError:
        pass
    return out


def alive(pid):
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


child_pid = None
router = None
try:
    with tempfile.TemporaryDirectory(prefix="znicz_cp_smoke_") as tmp:
        from znicz_tpu.resilience.chaos import _write_demo_znn

        model = os.path.join(tmp, "m.znn")
        state = os.path.join(tmp, "state")
        _write_demo_znn(model)
        rport = free_port()
        url = f"http://127.0.0.1:{rport}/"
        argv = [sys.executable, "-m", "znicz_tpu", "route",
                "--port", str(rport), "--autoscale",
                "--min-backends", "1", "--max-backends", "2",
                "--state-dir", state,
                "--reconcile-deadline-s", "20",
                "--probe-interval-s", "0.3",
                "--boot-timeout-s", "180",
                "--serve-arg=--model", f"--serve-arg={model}",
                "--serve-arg=--max-wait-ms", "--serve-arg=1"]

        def boot():
            return subprocess.Popen(argv, stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT)

        def wait_up(proc, what):
            for _ in range(360):
                try:
                    return healthz(url)
                except Exception:
                    if proc.poll() is not None:
                        print(f"FAIL {what} exited rc={proc.returncode}")
                        print(proc.stdout.read()
                              .decode(errors="replace")[-600:])
                        sys.exit(1)
                    time.sleep(0.5)
            print(f"FAIL {what} never answered /healthz")
            sys.exit(1)

        def wait_settled(what):
            for _ in range(150):
                rc = healthz(url).get("reconcile") or {}
                if rc.get("state") == "settled":
                    return True
                time.sleep(0.2)
            check(False, f"{what} never settled reconciliation")
            return False

        router = boot()
        wait_up(router, "router")
        wait_settled("first boot")
        boots = [e for e in journal(state) if e.get("kind") == "boot"]
        check(len(boots) == 1,
              f"first boot journals one child boot ({len(boots)})")
        child_pid = int(boots[0]["pid"]) if boots else None
        check(child_pid is not None and alive(child_pid),
              f"the managed child (pid {child_pid}) is alive")

        router.kill()                      # a CRASH, not a drain
        router.wait(timeout=15)
        check(child_pid is not None and alive(child_pid),
              "the child survives the router SIGKILL")

        router = boot()
        wait_up(router, "restarted router")
        wait_settled("restart")
        entries = journal(state)
        adopts = [e for e in entries if e.get("kind") == "adopt"]
        boots = [e for e in entries if e.get("kind") == "boot"]
        check(len(adopts) == 1
              and int(adopts[0]["pid"]) == child_pid,
              f"restart re-adopts the SAME pid {child_pid} "
              f"(adopts={[(e['backend'], e['pid']) for e in adopts]})")
        check(len(boots) == 1,
              f"zero double-boots ({len(boots)} boot records)")

        body = json.dumps({"inputs": [[0.1, -0.2, 0.3, 0.4]]}).encode()
        req = urllib.request.Request(
            url + "predict", body,
            {"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            r.read()
            check(r.status == 200,
                  "predict 200 through the re-adopted child")

        router.send_signal(signal.SIGTERM)
        try:
            rc = router.wait(timeout=60)
        except subprocess.TimeoutExpired:
            router.kill()
            rc = router.wait(timeout=10)
        check(rc == 0, f"router SIGTERM exit rc {rc}")
        check(child_pid is not None and alive(child_pid),
              "journal-and-keep: the child outlives SIGTERM for the "
              "next restart to re-adopt")
finally:
    if router is not None and router.poll() is None:
        router.kill()
    if child_pid is not None and alive(child_pid):
        os.kill(child_pid, signal.SIGTERM)
        for _ in range(100):
            if not alive(child_pid):
                break
            time.sleep(0.1)
        else:
            os.kill(child_pid, signal.SIGKILL)

print()
if fails:
    print(f"controlplane smoke: {len(fails)} failure(s)")
    sys.exit(1)
print("controlplane smoke: all checks passed")
PY
