#!/bin/bash
# Part-2 backlog: the rows the 2026-07-31 tunnel drop cut out of
# tools/burn_backlog.sh (the headline b128/b256/b512 sweep and the
# b128 --ablate landed before the relay died; everything below did
# not).  Append to the SAME transcript family so decide_levers.py can
# average across files: python tools/decide_levers.py backlog_r4*.jsonl
set -u -o pipefail
cd "$(dirname "$0")/.."
OUT="${1:-backlog_r4b.jsonl}"
run() {
  echo "### $*" >&2
  if ! timeout 3000 python "$@" 2> >(tail -5 >&2) \
      | tail -1 | tee -a "$OUT"; then
    echo "{\"error\": \"bench failed/timed out\", \"cmd\": \"$*\"}" \
      | tee -a "$OUT"
  fi
}

# the lever A/B rows decide_levers needs (both batches, each lever).
# Every row pins LRN_POOL EXPLICITLY so the transcript stays
# self-describing across default flips (round 5 shipped fused2 as the
# default; rows also carry bench.py's "resolved" routing field).
ZNICZ_TPU_LRN_POOL=fused1 run bench.py
ZNICZ_TPU_LRN_POOL=fused1 run bench.py --minibatch 256
ZNICZ_TPU_LRN_POOL=fused2 run bench.py
ZNICZ_TPU_LRN_POOL=fused2 run bench.py --minibatch 256
# s2d under BOTH pair contexts: under fused2 only conv1 can take s2d;
# under fused1 the pair-fed convs can too — separate verdicts
ZNICZ_TPU_LRN_POOL=fused2 ZNICZ_TPU_CONV1=s2d run bench.py
ZNICZ_TPU_LRN_POOL=fused2 ZNICZ_TPU_CONV1=s2d run bench.py --minibatch 256
ZNICZ_TPU_LRN_POOL=fused1 ZNICZ_TPU_CONV1=s2d run bench.py
ZNICZ_TPU_LRN_POOL=fused1 ZNICZ_TPU_CONV1=s2d run bench.py --minibatch 256
# verdicts land NOW, not only at burn end — a mid-burn tunnel drop
# must not eat the flip decision the rows above just bought.  On a
# fresh checkout backlog_r4.jsonl may not exist; only pass transcripts
# that do (decide_levers also warns-and-skips missing paths itself).
PRIOR=""
[ -f backlog_r4.jsonl ] && PRIOR="backlog_r4.jsonl"
python tools/decide_levers.py $PRIOR "$OUT" \
  | tee "$OUT.decisions.early" || true
# ORDER = decision value per minute of window: a short window must
# buy the flip confirmation and the precision headline candidates
# before the long kernel table / config refresh.
# precision / storage variants (storage rows depend on the diag's
# verdict on the r4 Mosaic failure; cheap to attempt either way)
run bench.py --dtype bfloat16
run bench.py --storage bfloat16
run bench.py --storage bfloat16 --minibatch 256
# the full-bf16 config — the max-throughput candidate (MXU bf16 peak
# is 2x f32)
run bench.py --dtype bfloat16 --storage bfloat16
# the lost ablation at b256 (under the new fused2 default; the A/B
# variant row is now lrn_pool_fused1)
run bench.py --ablate --minibatch 256
# data-plane: stream + on-device augment + loader-only
run bench.py --stream
run bench.py --augment
run bench.py --loader
run bench.py --loader --augment
# kernel table (24 rows incl. retiled convs + fused pair)
run bench.py --kernels
# non-alexnet config refresh (round-2 numbers are stale for the
# round-3/4 surface: merged pair kind, conv retile, VMEM block fix)
run bench.py --config mnist
run bench.py --config cifar
run bench.py --config autoencoder
run bench.py --config kohonen
# driver-side corroboration + lever verdicts over BOTH transcripts
{
  date -u +"# burn2 %Y-%m-%dT%H:%M:%SZ"
  grep -h "pallas_kernel_validation\|images_per_sec\|_ablation" "$OUT"
} >> kern_r4.log || true
python tools/decide_levers.py $PRIOR "$OUT" | tee "$OUT.decisions"
echo "backlog part 2 complete → $OUT (+ .decisions, kern_r4.log)" >&2
