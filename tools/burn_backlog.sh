#!/bin/bash
# On-chip measurement backlog (VERDICT r2 item 1): run EVERYTHING in one
# same-day session the moment the TPU tunnel answers.  Each line prints
# one JSON result; the transcript is the BASELINE.md refresh source.
#
# Usage:  bash tools/burn_backlog.sh [outfile]
set -u -o pipefail
cd "$(dirname "$0")/.."
OUT="${1:-backlog_$(date +%Y%m%d_%H%M%S).jsonl}"
run() {
  echo "### $*" >&2
  if ! timeout 3000 python "$@" 2> >(tail -5 >&2) \
      | tail -1 | tee -a "$OUT"; then
    # a killed/crashed bench must leave a marker, not a silent gap
    echo "{\"error\": \"bench failed/timed out\", \"cmd\": \"$*\"}" \
      | tee -a "$OUT"
  fi
}

# headline + batch sweep (fused pair merged = default)
run bench.py
run bench.py --minibatch 256
run bench.py --minibatch 512
# the LRN+pool merge A/B at both batches (rows full vs lrn_pool_split)
run bench.py --ablate
run bench.py --ablate --minibatch 256
# kernel table (now incl. lrn_maxpool/gd_lrn_maxpool + retiled convs)
run bench.py --kernels
# phase-2 split-conv candidate at both batches (opt-in lever)
ZNICZ_TPU_LRN_POOL=fused2 run bench.py
ZNICZ_TPU_LRN_POOL=fused2 run bench.py --minibatch 256
# conv1 space-to-depth candidate (round 4; also an --ablate row)
ZNICZ_TPU_CONV1=s2d run bench.py
ZNICZ_TPU_CONV1=s2d run bench.py --minibatch 256
# combination probe: NOTE under fused2 the pair-fed convs (conv1
# included) take the parity-split path, which s2d does not reach —
# this row isolates s2d's effect on the remaining plain convs only
ZNICZ_TPU_LRN_POOL=fused2 ZNICZ_TPU_CONV1=s2d run bench.py --minibatch 256
# precision / storage variants
run bench.py --dtype bfloat16
run bench.py --storage bfloat16 --minibatch 256
# data-plane: stream + on-device augment + loader-only
run bench.py --stream
run bench.py --augment
run bench.py --loader
run bench.py --loader --augment
# fresh driver-side corroboration outside BASELINE.md (VERDICT r3
# item 10): kernel table + headline lines, timestamped
{
  date -u +"# burn %Y-%m-%dT%H:%M:%SZ"
  grep -h "pallas_kernel_validation\|images_per_sec" "$OUT"
} >> kern_r4.log || true
# lever verdicts from the transcript (VERDICT r3 item 3): fused2 and
# conv1_s2d defaults get decided by measurement, same session
python tools/decide_levers.py "$OUT" | tee "$OUT.decisions"
echo "backlog complete → $OUT (+ .decisions, kern_r4.log)" >&2
