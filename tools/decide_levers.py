"""Read a burn-backlog transcript (JSONL) and print the lever verdicts.

VERDICT r3 item 3 requires the round to DECIDE the opt-in levers from
the measured A/B, not leave them as unmeasured debt.  This tool turns
``tools/burn_backlog.sh``'s transcript into explicit recommendations:

* ``ZNICZ_TPU_LRN_POOL=fused2`` — flip the default if the fused2
  headline beats the default merge at BOTH measured batches by more
  than the chip's observed run-to-run wobble (±15%: require >3% mean
  win with no loss at either batch).
* ``ZNICZ_TPU_CONV1=s2d`` — same rule.

Prints one JSON line: {"decisions": {...}, "evidence": {...}} and a
human table on stderr.  The flip itself stays a one-line change
(ops/tuning.py default) so the decision and its evidence land in the
same commit.

Usage: python tools/decide_levers.py backlog_*.jsonl
"""
import json
import sys


def load(paths):
    rows = []
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    print(f"skipping unparseable line in {p}: "
                          f"{line[:80]}", file=sys.stderr)
    return rows


#: the levers the decision compares; other ZNICZ_TPU_* vars (VMEM
#: budget, IO workers, interpret mode...) are tuning context, not
#: routing choices — an ambient one must not break tag matching
_ROUTING = ("ZNICZ_TPU_LRN_POOL", "ZNICZ_TPU_CONV1", "ZNICZ_TPU_CONV",
            "ZNICZ_TPU_NO_PALLAS", "ZNICZ_TPU_MXU")


def headline(rows):
    """{(lever_tag, minibatch): mean images/sec} for AlexNet training
    rows on a real (non-cpu-fallback) device.  Repeated measurements
    of the same configuration (burn re-runs, multiple transcripts)
    AVERAGE — the ±15%-wobble argument behind the 3% threshold assumes
    means, not an arbitrary last sample."""
    acc = {}
    for r in rows:
        if r.get("metric") != "alexnet_train_images_per_sec_per_chip" \
                or r.get("value") is None:
            continue
        if "cpu" in str(r.get("device", "")).lower():
            continue                      # fallback rows decide nothing
        lv = r.get("levers", {})
        tag = ",".join(f"{k.replace('ZNICZ_TPU_', '')}={v}"
                       for k, v in lv.items()
                       if k in _ROUTING) or "default"
        acc.setdefault((tag, r.get("minibatch")), []).append(r["value"])
    for key, vals in acc.items():
        if len(vals) > 1:
            print(f"  averaging {len(vals)} samples for {key}",
                  file=sys.stderr)
    return {k: round(sum(v) / len(v), 1) for k, v in acc.items()}


def decide(hl, lever_tag):
    """(decision, evidence) comparing `lever_tag` rows to default."""
    pairs = []
    for (tag, mb), v in hl.items():
        if tag == lever_tag and ("default", mb) in hl:
            pairs.append((mb, hl[("default", mb)], v))
    if not pairs:
        return "no-data", {"pairs": []}
    gains = [(v - base) / base for _, base, v in pairs]
    win = (min(gains) > 0 and sum(gains) / len(gains) > 0.03)
    ev = {"pairs": [{"minibatch": mb, "default": base, "lever": v,
                     "gain_pct": round(100 * (v - base) / base, 1)}
                    for mb, base, v in pairs]}
    # "both measured batches": one surviving pair (the other bench run
    # timed out) is not enough evidence to flip a default
    if len(pairs) < 2:
        return ("insufficient-data (re-run the missing batch)"
                if win else "keep-off"), ev
    return ("flip-default" if win else "keep-off"), ev


def main(argv):
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    rows = load(argv)
    hl = headline(rows)
    if not hl:
        print(json.dumps({"decisions": {},
                          "error": "no on-device headline rows in "
                                   "transcript"}))
        return 1
    decisions, evidence = {}, {}
    for lever, tag in (("ZNICZ_TPU_LRN_POOL=fused2",
                        "LRN_POOL=fused2"),
                       ("ZNICZ_TPU_CONV1=s2d", "CONV1=s2d")):
        decisions[lever], evidence[lever] = decide(hl, tag)
    for (tag, mb), v in sorted(hl.items()):
        print(f"  {tag:24s} b{mb}: {v} img/s", file=sys.stderr)
    for lever, d in decisions.items():
        print(f"  {lever}: {d}", file=sys.stderr)
    print(json.dumps({"decisions": decisions, "evidence": evidence}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
