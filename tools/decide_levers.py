"""Read burn-backlog transcripts (JSONL) and print the lever verdicts.

VERDICT r3 item 3 requires the round to DECIDE the opt-in levers from
the measured A/B, not leave them as unmeasured debt.  This tool turns
``tools/burn_backlog*.sh`` transcripts into explicit recommendations.

Round-5 semantics (the fused2 default FLIPPED this round, per VERDICT
r4 item 1, on the 1.78× on-chip b128 ablation):

* ``LRN_POOL fused2 vs fused1`` — fused2 is now the DEFAULT.  With
  both batches measured, the verdict is ``keep-default-fused2`` if
  fused2 beats fused1 by >3% mean with no loss at either batch (the
  original flip rule, now confirming the flip), ``revert-to-fused1``
  on a loss at EITHER batch (the symmetric promise in the shipped
  default's risk note), else ``marginal-keep``.  One surviving batch
  is ``insufficient-data`` — it can neither confirm nor revert.
* ``CONV1 s2d vs direct`` — still opt-in: ``flip-default`` on a >3%
  mean win with no loss at both batches, else ``keep-off``.  s2d is
  evaluated separately under each LRN_POOL context it was measured in
  (under fused2 only conv1 can take s2d; under fused1 the pair-fed
  convs can too), because the verdict may differ.

Rows are compared by their **resolved routing** (the ``resolved``
field bench.py stamps since round 5 — env levers + defaults already
applied) and their **code revision** (the ``rev`` sha stamped since
round 6): rows from different revisions neither average nor pair, so
a keep/revert verdict never mixes measurements of different code.
Pre-round-5 rows carry only explicit env levers; they are
canonicalized against the ROUND-4 defaults they actually ran under
(LRN_POOL=fused1, CONV1=direct, CONV=xla, PALLAS=on, MXU=bf16), so
"no levers" rows from backlog_r4.jsonl keep meaning fused1 even though
today's default is fused2.

Prints one JSON line: {"decisions": {...}, "evidence": {...}} and a
human table on stderr.

Usage: python tools/decide_levers.py backlog_*.jsonl
"""
import json
import sys

#: defaults pre-round-5 transcript rows (no ``resolved`` field) ran
#: under — the canonicalization target for legacy "levers"-only rows
_LEGACY_DEFAULTS = {"LRN_POOL": "fused1", "CONV1": "direct",
                    "CONV": "xla", "PALLAS": "on", "MXU": "bf16"}
_ROUTING_KEYS = tuple(_LEGACY_DEFAULTS)


def load(paths):
    """Rows from every transcript that can be read; a missing or
    unreadable file (fresh checkout, renamed burn output) warns on
    stderr and is skipped — it must not traceback into a
    silently-empty .decisions file."""
    rows = []
    for p in paths:
        try:
            f = open(p)
        except OSError as e:
            print(f"warning: cannot read transcript {p} ({e}), "
                  f"skipping", file=sys.stderr)
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    print(f"skipping unparseable line in {p}: "
                          f"{line[:80]}", file=sys.stderr)
    return rows


def canonical(row):
    """Resolved routing config for a transcript row, as a hashable
    sorted-items tuple."""
    res = row.get("resolved")
    if not isinstance(res, dict):
        res = dict(_LEGACY_DEFAULTS)
        lv = row.get("levers", {})
        if "ZNICZ_TPU_LRN_POOL" in lv:
            val = lv["ZNICZ_TPU_LRN_POOL"]
            # legacy "fused" meant the then-default merge+fold phase-1
            res["LRN_POOL"] = "fused1" if val == "fused" else val
        if lv.get("ZNICZ_TPU_CONV1") == "s2d":
            res["CONV1"] = "s2d"
        if lv.get("ZNICZ_TPU_CONV") == "pallas":
            res["CONV"] = "pallas"
        if lv.get("ZNICZ_TPU_NO_PALLAS") == "1":
            res["PALLAS"] = "off"
        if lv.get("ZNICZ_TPU_MXU"):
            res["MXU"] = lv["ZNICZ_TPU_MXU"].lower()
    cfg = {k: res.get(k, _LEGACY_DEFAULTS[k]) for k in _ROUTING_KEYS}
    return tuple(sorted(cfg.items()))


def headline(rows):
    """{(config, minibatch, rev): mean images/sec} for AlexNet training
    rows on a real (non-cpu-fallback) device.  Repeated measurements of
    the same configuration (burn re-runs, multiple transcripts) AVERAGE
    — the ±15%-wobble argument behind the 3% threshold assumes means,
    not an arbitrary last sample.

    The code revision (the ``rev`` sha bench.py stamps since round 6)
    is part of the key: rows measured on different code must neither
    average together nor pair as an A/B — a lever verdict drawn across
    a code change measures the change, not the lever (ADVICE r5).
    Pre-stamp rows carry rev None and keep pairing among themselves."""
    acc = {}
    for r in rows:
        if r.get("metric") != "alexnet_train_images_per_sec_per_chip" \
                or r.get("value") is None:
            continue
        if "cpu" in str(r.get("device", "")).lower():
            continue                      # fallback rows decide nothing
        # the sharding scheme keys like the minibatch: a "4x2" mesh row
        # and a "1x1" row measure different programs and must neither
        # average nor pair (legacy rows predate the stamp and were all
        # single-device, so they canonicalize to "1x1")
        acc.setdefault((canonical(r), r.get("minibatch"),
                        r.get("rev"), r.get("sharding") or "1x1"),
                       []).append(r["value"])
    for key, vals in acc.items():
        if len(vals) > 1:
            cfg, mb, rev, sharding = key
            print(f"  averaging {len(vals)} samples for "
                  f"{_short(cfg)} b{mb} s{sharding}"
                  + (f" @{rev}" if rev else ""), file=sys.stderr)
    return {k: round(sum(v) / len(v), 1) for k, v in acc.items()}


#: today's SHIPPED routing defaults (fused2 since round 5) — the one
#: copy in this module; must mirror znicz_tpu/ops/tuning.py
#: resolved_routing()'s defaults, which cannot be imported here because
#: importing znicz_tpu triggers jax backend init (hangs on a dead
#: tunnel).  tests/test_decide_levers.py pins the two in sync.
_SHIPPED = {"LRN_POOL": "fused2", "CONV1": "direct", "CONV": "xla",
            "PALLAS": "on", "MXU": "bf16"}


def _short(cfg):
    """Compact human tag: only the keys that differ from the shipped
    defaults."""
    parts = [f"{k}={v}" for k, v in sorted(dict(cfg).items())
             if _SHIPPED.get(k) != v]
    return ",".join(parts) or "default"


def compare(hl, key, challenger, baseline):
    """All (minibatch, context) pairs where a challenger-config row has
    a baseline twin differing ONLY in `key` — same minibatch, same
    code revision (a pair straddling a code change measures the code
    change, not the lever), and same sharding scheme (a mesh row and a
    single-device row measure different programs)."""
    pairs = []
    # rows without a minibatch field sort as 0, not TypeError
    for (cfg, mb, rev, sharding), v in sorted(
            hl.items(), key=lambda kv: (kv[0][1] or 0, kv[0][0],
                                        kv[0][2] or "", kv[0][3])):
        d = dict(cfg)
        if d.get(key) != challenger:
            continue
        d[key] = baseline
        bk = (tuple(sorted(d.items())), mb, rev, sharding)
        if bk in hl:
            ctx = {k: v2 for k, v2 in cfg if k != key}
            pairs.append({"minibatch": mb, "rev": rev,
                          "sharding": sharding, "context": _short(
                tuple(sorted(ctx.items()))),
                # decided against the cfg itself, not the display tag
                "shipped_context": all(
                    _SHIPPED.get(k) == v2 for k, v2 in ctx.items()),
                "baseline": hl[bk], "challenger": v,
                "gain_pct": round(100 * (v - hl[bk]) / hl[bk], 1)})
    return pairs


def rev_order(rows):
    """{rev: latest ISO ts} over headline-eligible rows — orders code
    revisions by when they were last measured (ISO timestamps sort
    lexicographically).  The rev=None pseudo-revision is never entered:
    unstamped rows must sort OLDEST regardless of their ts, or one
    fresh no-git row would let stale legacy pairs outrank a cleanly
    stamped revision's verdict."""
    order = {}
    for r in rows:
        if r.get("metric") != "alexnet_train_images_per_sec_per_chip" \
                or r.get("value") is None:
            continue
        if "cpu" in str(r.get("device", "")).lower():
            continue
        rev = r.get("rev")
        if rev is None:
            continue
        ts = str(r.get("ts") or "")
        if ts >= order.get(rev, ""):
            order[rev] = ts
    return order


def _qualified(pairs, order=None):
    """Pairs from ONE (revision, sharding) context that measured BOTH
    batches: the two-batch sufficiency rule must hold within one code
    revision AND one sharding scheme (a b128 pair from rev A plus a
    b256 pair from rev B is two single-batch observations of different
    code; a b128 1x1 pair plus a b256 4x2 pair is two single-batch
    observations of different PROGRAMS), and when several contexts
    each carry a complete A/B, the newest revision decides — with the
    single-device scheme preferred at equal recency, because lever
    defaults ship for the single-device program."""
    by_ctx = {}
    for p in pairs:
        by_ctx.setdefault((p.get("rev"), p.get("sharding") or "1x1"),
                          set()).add(p["minibatch"])
    full = [ctx for ctx, mbs in by_ctx.items() if len(mbs) >= 2]
    if not full:
        return []
    order = order or {}
    winner = max(full, key=lambda c: (
        order.get(c[0], ""),
        c[1] == "1x1",                               # shipped program
        sum(1 for p in pairs                         # deterministic
            if (p.get("rev"), p.get("sharding") or "1x1") == c),
        c[0] or ""))                                 # tie-breakers
    return [p for p in pairs
            if (p.get("rev"), p.get("sharding") or "1x1") == winner]


def _win(pairs, order=None):
    """The codified rule: >3% mean gain with no loss at either batch,
    and at least two measured batches (one surviving pair — the other
    bench run timed out — is not enough evidence) — within a single
    code revision (see _qualified)."""
    pairs = _qualified(pairs, order)
    if len({p["minibatch"] for p in pairs}) < 2:
        return None
    gains = [p["gain_pct"] / 100 for p in pairs]
    return min(gains) > 0 and sum(gains) / len(gains) > 0.03


def lrn_pool_verdict(pairs, order=None):
    """Verdict on the SHIPPED default, so only pairs measured in the
    shipped context (every other routing key at its default, i.e.
    CONV1=direct) decide it: the burn also measures fused2-vs-fused1
    under CONV1=s2d, and a loss in that opt-in context must not veto a
    default that wins where it ships (nor may a b128-s2d pair plus a
    b256-direct pair masquerade as "both batches measured")."""
    pairs = [p for p in pairs if p.get("shipped_context")]
    if not pairs:
        return "no-data (flip stands on the r4 ablation; re-run the " \
               "A/B)"
    # qualify ONCE: the win test and the revert evidence below must be
    # drawn from the same pair set (_qualified is idempotent, so the
    # nested call inside _win re-selects the same pairs)
    pairs = _qualified(pairs, order) or pairs
    win = _win(pairs, order)
    if win is None:
        # one surviving batch can neither confirm nor revert a
        # default — a single noisy pair is exactly the ±15% wobble the
        # two-batch rule exists to exclude
        return "insufficient-data (re-run the missing batch)"
    if win:
        return "keep-default-fused2 (confirmed)"
    # the revert is decided by the same evidence set the win rule uses:
    # the qualified (both-batch, newest-revision) pairs selected above
    losses = [p for p in pairs if p["gain_pct"] < 0]
    if losses:
        # the shipped default's own risk note (tuning.py
        # lrn_pool_split_conv) promises a revert on a loss at EITHER
        # batch — symmetric with the no-loss-both-batches rule that
        # would have gated the flip
        return "revert-to-fused1 (loss at " + ", ".join(
            f"b{p['minibatch']}: {p['gain_pct']}%" for p in losses) + ")"
    return "marginal-keep (within wobble)"


def conv1_verdicts(pairs, order=None):
    """Per-context verdicts: under fused2 only conv1 can take s2d,
    under fused1 the pair-fed convs can too — pooling the contexts
    would let one context's loss veto the other's win."""
    if not pairs:
        return "no-data"
    out = {}
    for ctx in sorted({p["context"] for p in pairs}):
        cp = [p for p in pairs if p["context"] == ctx]
        win = _win(cp, order)
        out[ctx] = ("flip-default" if win
                    else "insufficient-data (re-run the missing batch)"
                    if win is None else "keep-off")
    return out


def main(argv):
    if not argv:
        print(__doc__, file=sys.stderr)
        return 2
    rows = load(argv)
    hl = headline(rows)
    if not hl:
        print(json.dumps({"decisions": {},
                          "error": "no on-device headline rows in "
                                   "transcript"}))
        return 1
    decisions, evidence = {}, {}
    order = rev_order(rows)

    pairs = compare(hl, "LRN_POOL", "fused2", "fused1")
    evidence["LRN_POOL fused2 vs fused1"] = pairs
    decisions["LRN_POOL"] = lrn_pool_verdict(pairs, order)

    pairs = compare(hl, "CONV1", "s2d", "direct")
    evidence["CONV1 s2d vs direct"] = pairs
    decisions["CONV1"] = conv1_verdicts(pairs, order)

    for (cfg, mb, rev, sharding), v in sorted(
            hl.items(), key=lambda kv: (kv[0][1] or 0,
                                        _short(kv[0][0]),
                                        kv[0][2] or "", kv[0][3])):
        print(f"  {_short(cfg):36s} b{mb}"
              + (f" s{sharding}" if sharding != "1x1" else "")
              + (f" @{rev}" if rev else "")
              + f": {v} img/s", file=sys.stderr)
    for lever, d in decisions.items():
        print(f"  {lever}: {d}", file=sys.stderr)
    print(json.dumps({"decisions": decisions, "evidence": evidence}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
