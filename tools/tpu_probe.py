"""Persistent TPU liveness probe with bounded retry/backoff.

VERDICT r2 item 1 asks for bounded retry/backoff around the PJRT probe so a
transient tunnel flap doesn't cost the round.  This script probes in a
subprocess (PJRT init can hang, not just fail), backing off between
attempts, and writes /root/repo/.tpu_status.json after every attempt:
  {"up": bool, "attempt": N, "ts": ..., "detail": ...}
Exits 0 the moment a probe sees a real TPU device; exits 1 after the
deadline (default 11h) with the TPU never answering.
"""
import json
import os
import subprocess
import sys
import time

STATUS = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", ".tpu_status.json")
PROBE = (
    "import jax, json; ds = jax.devices(); "
    "print(json.dumps({'platform': ds[0].platform, 'n': len(ds), 'kind': getattr(ds[0], 'device_kind', '?')}))"
)


def probe_once(timeout):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let PJRT pick the TPU plugin
    try:
        out = subprocess.run(
            [sys.executable, "-c", PROBE], capture_output=True, text=True,
            timeout=timeout, env=env,
        )
    except subprocess.TimeoutExpired:
        return None, "probe hung (%ds timeout)" % timeout
    if out.returncode != 0:
        return None, (out.stderr or "rc=%d" % out.returncode)[-300:]
    try:
        info = json.loads(out.stdout.strip().splitlines()[-1])
    except Exception:
        return None, "unparseable: %r" % out.stdout[-200:]
    if info.get("platform") == "tpu":
        return info, "tpu up"
    return None, "platform=%s (cpu fallback, tunnel down)" % info.get("platform")


def main():
    deadline = time.time() + float(os.environ.get("TPU_PROBE_DEADLINE_S", 11 * 3600))
    attempt = 0
    backoff = 60.0
    while time.time() < deadline:
        attempt += 1
        info, detail = probe_once(timeout=180)
        rec = {"up": info is not None, "attempt": attempt, "ts": time.time(),
               "detail": detail, "info": info}
        with open(STATUS, "w") as f:
            json.dump(rec, f)
        print("[probe %d] %s" % (attempt, detail), flush=True)
        if info is not None:
            return 0
        time.sleep(backoff)
        backoff = min(backoff * 1.5, 600.0)
    return 1


if __name__ == "__main__":
    sys.exit(main())
