"""Persistent TPU liveness probe with bounded retry/backoff.

VERDICT r2 item 1 asks for bounded retry/backoff around the PJRT probe
so a transient tunnel flap doesn't cost the round.  Round-4 diagnosis:
the axon plugin reaches the chip through a local relay
(`PALLAS_AXON_POOL_IPS`, gRPC on :8082/:8083); when the relay is down
the ports REFUSE instantly but PJRT's channel retries forever — the
observed "hang".  So the probe now does a ~20 ms TCP pre-check of the
relay port and only pays the heavyweight PJRT subprocess probe once
the port accepts; while the port refuses it rechecks every 20 s
instead of burning 180 s per attempt, catching a tunnel restoration
within seconds.

Writes /root/repo/.tpu_status.json after every attempt:
  {"up": bool, "attempt": N, "ts": ..., "detail": ...}
Exits 0 the moment a probe sees a real accelerator; exits 1 after the
deadline (default 11h) with the TPU never answering.
"""
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
from znicz_tpu.tpu_liveness import relay_endpoint, relay_ok  # noqa: E402

STATUS = os.path.join(_REPO, ".tpu_status.json")
PROBE = (
    "import jax, json; ds = jax.devices(); "
    "print(json.dumps({'platform': ds[0].platform, 'n': len(ds), 'kind': getattr(ds[0], 'device_kind', '?')}))"
)


def probe_once(timeout):
    env = dict(os.environ)
    # a wrapper may have pinned the platform to CPU (conftest-style);
    # the probe must let PJRT pick the accelerator plugin
    env.pop("JAX_PLATFORMS", None)
    try:
        out = subprocess.run(
            [sys.executable, "-c", PROBE], capture_output=True, text=True,
            timeout=timeout, env=env,
        )
    except subprocess.TimeoutExpired:
        return None, "probe hung (%ds timeout)" % timeout
    if out.returncode != 0:
        return None, (out.stderr or "rc=%d" % out.returncode)[-300:]
    try:
        info = json.loads(out.stdout.strip().splitlines()[-1])
    except Exception:
        return None, "unparseable: %r" % out.stdout[-200:]
    # the tunneled plugin may report its platform as "tpu" OR "axon" —
    # anything that isn't the host CPU/GPU is the accelerator
    # (same rule as ops/tuning.on_tpu)
    if info.get("platform") not in ("cpu", "gpu"):
        return info, "tpu up (platform=%s)" % info.get("platform")
    return None, "platform=%s (cpu fallback, tunnel down)" % info.get("platform")


def write_status(up, attempt, detail, info=None):
    rec = {"up": up, "attempt": attempt, "ts": time.time(),
           "detail": detail, "info": info}
    with open(STATUS, "w") as f:
        json.dump(rec, f)
    print("[probe %d] %s" % (attempt, detail), flush=True)


def main():
    deadline = time.time() + float(os.environ.get("TPU_PROBE_DEADLINE_S", 11 * 3600))
    attempt = 0                 # REAL PJRT probes only — the cheap
    port_checks = 0             # port checks count separately
    backoff = 60.0
    last_port_note = 0.0
    while time.time() < deadline:
        if not relay_ok():
            port_checks += 1
            # cheap loop: note the closed port at most once a minute,
            # recheck every 20 s — a restoration is caught in seconds
            # (relay_ok() is True when no relay is configured, so a
            # direct-attached TPU skips straight to the PJRT probe)
            if time.time() - last_port_note > 60:
                write_status(False, attempt,
                             "relay port %s:%d refused (tunnel down; "
                             "%d port checks)"
                             % (*relay_endpoint(), port_checks))
                last_port_note = time.time()
            time.sleep(20)
            continue
        attempt += 1
        info, detail = probe_once(timeout=180)
        write_status(info is not None, attempt, detail, info)
        if info is not None:
            return 0
        time.sleep(backoff)
        backoff = min(backoff * 1.5, 600.0)
    return 1


if __name__ == "__main__":
    sys.exit(main())
