#!/bin/bash
# Serving-under-fault smoke (ISSUE 2 acceptance, operator-runnable):
# boot the HTTP serving stack under a canned engine.forward fault plan
# and assert graceful degradation end to end — every request resolves
# as a native-fallback 200 or 503 + Retry-After (never a hang, never a
# raw 500), /healthz goes degraded while the circuit is open, and the
# breaker closes again via a half-open probe once the fault clears.
#
# `--scenario reload` (ISSUE 5 acceptance) instead drills the
# durability layer: a hot reload of a deterministically bit-rotted
# artifact (the artifact.bitflip fault site) must roll back — verify
# fails, the generation stays put, the OLD model keeps serving 200s
# with identical bytes — and a good artifact must then swap with zero
# downtime (docs/durability.md).
#
# Usage:  bash tools/chaos_smoke.sh [chaos-mode args...]
#         (e.g. --model my.znn --plan @plan.json --requests 20,
#          or --scenario reload;
#          see `python -m znicz_tpu chaos --help` / docs/resilience.md)
set -u -o pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m znicz_tpu chaos "$@"
