"""Isolate the bf16-storage remote-compile failure (burn r4: the
--ablate ``storage_bf16`` variant died in tpu_compile_helper while every
f32 variant compiled).  Compiles each Pallas kernel family at the real
AlexNet pair geometries with bf16 inputs, one at a time, printing
PASS/FAIL per family so the first failing compile names the kernel
instead of the whole fused step.

Run ON the chip (tunnel up): python tools/diag_bf16_storage.py
(--tiny: small shapes, for signature/CI validation in interpret mode)
"""
import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

if "--tiny" in sys.argv:
    # CI/signature validation off-chip needs interpret-mode Pallas —
    # tuning._INTERPRET reads the env at import, so set it BEFORE any
    # znicz_tpu import or every case FAILs with a Pallas-unsupported
    # error on CPU (ADVICE r4)
    os.environ["ZNICZ_TPU_PALLAS_INTERPRET"] = "1"
    # the sitecustomize pins the axon platform regardless of
    # JAX_PLATFORMS, so pin CPU post-import (conftest pattern) or a
    # dead tunnel hangs device init forever
    import jax
    jax.config.update("jax_platforms", "cpu")


def main():
    import jax.numpy as jnp
    from znicz_tpu.ops import elementwise, lrn_pool, matmul, pooling

    rng = np.random.default_rng(7)
    tiny = "--tiny" in sys.argv

    def bf16(*s):
        return jnp.asarray(rng.standard_normal(s), jnp.bfloat16)

    cases = []

    # the two AlexNet pair geometries, bf16 storage
    pair_shapes = ([(2, 7, 7, 8)] if tiny
                   else [(128, 55, 55, 96), (128, 27, 27, 256)])
    for shape in pair_shapes:
        x = bf16(*shape)
        xe, xo = lrn_pool.split_cols(x)

        def pair_fwd(xe=xe, xo=xo):
            y, idx = lrn_pool.pallas_lrn_maxpool_split(
                xe, xo, 5, 1e-4, 0.75, 2.0, (3, 3), (2, 2), 0)
            y.block_until_ready()
            return y, idx

        def pair_bwd(xe=xe, xo=xo):
            y, idx = lrn_pool.pallas_lrn_maxpool_split(
                xe, xo, 5, 1e-4, 0.75, 2.0, (3, 3), (2, 2), 0)
            dx = lrn_pool.pallas_gd_lrn_maxpool_split(
                y * jnp.bfloat16(0.1), idx, xe, xo, 5, 1e-4, 0.75,
                2.0, (3, 3), (2, 2), 0, fold_act="strict_relu")
            return dx.block_until_ready()

        cases.append((f"lrn_pool fwd {shape}", pair_fwd))
        cases.append((f"lrn_pool bwd+fold {shape}", pair_bwd))

    x2 = bf16(8, 32) if tiny else bf16(128, 4096)
    cases.append(("act fwd relu bf16",
                  lambda: elementwise.pallas_act_fwd(
                      "relu", x2).block_until_ready()))
    cases.append(("act bwd tanh bf16",
                  lambda: elementwise.pallas_act_bwd(
                      "tanh", x2, x2).block_until_ready()))
    cases.append(("dropout bf16",
                  lambda: elementwise.pallas_dropout(
                      x2, 1234, (0, 0, 0), 0.5)[0].block_until_ready()))
    a, b = ((bf16(16, 32), bf16(32, 24)) if tiny
            else (bf16(512, 9216), bf16(9216, 4096)))
    cases.append(("matmul bf16",
                  lambda: matmul.pallas_matmul(a, b).block_until_ready()))
    xp_ = bf16(2, 7, 7, 8) if tiny else bf16(128, 27, 27, 256)
    cases.append(("pool_select bf16",
                  lambda: pooling.max_pooling(
                      xp_, (3, 3), (2, 2), 0)[0].block_until_ready()))

    failed = 0
    for name, thunk in cases:
        try:
            thunk()
            print(f"PASS {name}")
        except Exception as e:
            failed += 1
            print(f"FAIL {name}: {e!r}"[:2000])
            traceback.print_exc(limit=2)
    print(f"{len(cases) - failed}/{len(cases)} pass")
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
