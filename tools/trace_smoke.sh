#!/bin/bash
# Distributed-tracing smoke (ISSUE 18, operator-runnable): boot the
# REAL fleet — `python -m znicz_tpu route` over two real `serve`
# backends — fire a mixed burst, then assert the cross-hop tracing
# contract end to end:
#   * the router's GET /tracez holds >= 1 assembled multi-hop trace;
#   * every assembled trace carries ALL seven canonical stages
#     (tracestore.STAGES) as non-negative durations;
#   * each trace's stage sum reconciles with its end-to-end wall
#     (within tolerance: the stages are clamped monotonic gaps);
#   * a client-supplied X-Znicz-Trace id is honored (continue, never
#     re-root) and the response hands back the assembled per-stage
#     split in X-Znicz-Spans.
#
# Deeper drills (fault-dominated stages, refusal retention, bench
# decomposition) live in `chaos --scenario trace`; this is the quick
# always-green slice, registered beside tools/metrics_smoke.sh.
#
# Usage:  bash tools/trace_smoke.sh [n_requests]
set -u -o pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python - "${1:-24}" <<'PY'
import json, os, signal, socket, subprocess, sys, tempfile, time
import urllib.request

from znicz_tpu.telemetry import tracestore, tracing

n_req = int(sys.argv[1])
fails = []


def check(cond, msg):
    print(("ok  " if cond else "FAIL") + " " + msg)
    if not cond:
        fails.append(msg)


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_healthz(url, proc, what):
    for _ in range(240):
        try:
            urllib.request.urlopen(url + "healthz", timeout=2)
            return
        except Exception:
            if proc.poll() is not None:
                out = proc.stdout.read().decode(errors="replace")
                sys.exit(f"{what} exited rc={proc.returncode}:\n"
                         + out[-2000:])
            time.sleep(0.25)
    sys.exit(f"{what} never answered /healthz")


procs = []
with tempfile.TemporaryDirectory(prefix="znicz_trace_smoke_") as tmp:
    model = os.path.join(tmp, "demo.znn")
    from znicz_tpu.resilience.chaos import _write_demo_znn
    _write_demo_znn(model)
    bports = [free_port(), free_port()]
    rport = free_port()
    try:
        for port in bports:
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "znicz_tpu", "serve",
                 "--model", model, "--port", str(port),
                 "--max-wait-ms", "1", "--warmup-shape", "4"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        for i, port in enumerate(bports):
            wait_healthz(f"http://127.0.0.1:{port}/", procs[i],
                         f"backend {i}")
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "znicz_tpu", "route",
             "--port", str(rport),
             "--trace-sample", "1.0", "--trace-head-rate", "1.0"]
            + [f for i, port in enumerate(bports)
               for f in ("--backend",
                         f"http://127.0.0.1:{port}/,name=b{i}")],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        url = f"http://127.0.0.1:{rport}/"
        wait_healthz(url, procs[-1], "router")

        body = json.dumps({"inputs": [[0.1, -0.2, 0.3, 0.4]]}).encode()
        for _ in range(n_req):           # router-rooted traffic
            req = urllib.request.Request(
                url + "predict", body,
                {"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                pass

        # one client-rooted request: the router must CONTINUE the
        # supplied context and answer with the assembled stage split
        ctx = tracing.TraceContext(tracing.new_trace_id(),
                                   tracing.new_span_id())
        req = urllib.request.Request(
            url + "predict", body,
            {"Content-Type": "application/json",
             tracestore.TRACE_HEADER: tracing.format_traceparent(ctx)})
        with urllib.request.urlopen(req, timeout=60) as r:
            spans_hdr = r.headers.get(tracestore.SPANS_HEADER)
        summary = tracestore.decode_summary(spans_hdr)
        check(summary is not None,
              "client-traced request answered with X-Znicz-Spans")
        check(summary is not None
              and summary.get("trace_id") == ctx.trace_id,
              "router continued the client's trace id (no re-root)")
        check(summary is not None
              and set(summary.get("stages") or {}) ==
              set(tracestore.STAGES),
              "in-band split carries all seven stages")

        with urllib.request.urlopen(url + "tracez", timeout=10) as r:
            tz = json.loads(r.read())
        traces = tz.get("traces") or []
        check(len(traces) >= 1,
              f"/tracez holds assembled traces ({len(traces)})")
        check(any(t.get("trace_id") == ctx.trace_id for t in traces),
              "client-rooted trace retained in the store")
        full = [t for t in traces
                if set(t.get("stages") or {}) == set(tracestore.STAGES)
                and all(v >= 0.0 for v in t["stages"].values())]
        check(len(full) >= 1,
              f"multi-hop traces carry all seven stages as "
              f"non-negative durations ({len(full)}/{len(traces)})")
        backends = {t.get("backend") for t in full}
        check(len(backends) >= 2,
              f"traces span both backends (saw {sorted(backends)})")
        recon = bad_recon = 0
        for t in full:
            ssum = sum(t["stages"].values())
            tol = max(0.15 * t["total_ms"], 1.0)
            if abs(ssum - t["total_ms"]) <= tol:
                recon += 1
            else:
                bad_recon += 1
        check(bad_recon == 0 and recon >= 1,
              f"stage sum ~= e2e wall on every full trace "
              f"({recon} ok, {bad_recon} off)")
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()

print(json.dumps({"ok": not fails, "violations": fails}))
sys.exit(1 if fails else 0)
PY
