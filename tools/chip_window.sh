#!/bin/bash
# Exploit the next TPU window automatically: wait for the persistent
# probe (tools/tpu_probe.py) to flip .tpu_status.json to up, pause any
# CPU-hogging background job (this host has ONE core — a convergence
# run starves the axon compile-helper), run the bf16-storage kernel
# diagnostic, then burn the part-2 backlog.  Resumes the paused job
# when done or on exit.
#
# Usage: bash tools/chip_window.sh [pause_pid]
set -u
cd "$(dirname "$0")/.."
PAUSE_PID="${1:-}"

resume() {
  if [ -n "$PAUSE_PID" ] && kill -0 "$PAUSE_PID" 2>/dev/null; then
    kill -CONT "$PAUSE_PID" 2>/dev/null && echo "resumed $PAUSE_PID" >&2
  fi
}
trap resume EXIT

echo "waiting for tunnel (probe writes .tpu_status.json)..." >&2
while true; do
  up=$(python -c "
import json
try: print(json.load(open('.tpu_status.json'))['up'])
except Exception: print(False)" 2>/dev/null)
  [ "$up" = "True" ] && break
  sleep 15
done
echo "tunnel UP at $(date -u +%H:%M:%SZ)" >&2

if [ -n "$PAUSE_PID" ] && kill -0 "$PAUSE_PID" 2>/dev/null; then
  kill -STOP "$PAUSE_PID" 2>/dev/null && echo "paused $PAUSE_PID" >&2
fi

# name the bf16-storage Mosaic failure first (cheap, informs the
# --storage row's interpretation), then burn the decision-critical rows
timeout 1200 python tools/diag_bf16_storage.py > diag_bf16.out 2>&1
diag_rc=$?
echo "diag done (rc=$diag_rc) → diag_bf16.out" >&2
if [ "$diag_rc" -ne 0 ]; then
  # the burn still runs (the A/B rows are the scarcer evidence), but
  # the window's transcript must record loudly that the bf16
  # diagnostic did not complete — rc 124 is the 1200 s timeout
  marker="### DIAG FAILED rc=$diag_rc ($(date -u +%H:%M:%SZ)) — bf16-storage kernel family NOT isolated this window"
  echo "$marker" >&2
  echo "$marker" | tee -a diag_bf16.out >> kern_r4.log
fi
bash tools/burn_backlog2.sh backlog_r4b.jsonl
