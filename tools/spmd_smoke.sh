#!/bin/bash
# SPMD smoke (ISSUE 8 acceptance, operator-runnable): on 8 forced host
# devices,
#   1. a mesh-sharded fused train step (dp=4 x tp=2) through the PUBLIC
#      StandardWorkflow.train(mesh_shape=...) entry point matches the
#      single-device loss trajectory, with params genuinely laid out
#      over all 8 devices;
#   2. the REAL `python -m znicz_tpu serve --replicas 2 --tp 2` CLI
#      serves a concurrent burst with ZERO non-200s, /healthz reports
#      the mesh + per-replica breaker state, and /statusz carries the
#      replica table.
#
# Registered beside tools/metrics_smoke.sh / tools/chaos_smoke.sh;
# tier-1 twin: tests/test_spmd.py.
#
# Usage:  bash tools/spmd_smoke.sh [burst_requests]
set -u -o pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python - "${1:-24}" <<'PY'
import json, os, socket, subprocess, sys, tempfile, threading, time
import urllib.error, urllib.request

import jax
jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, "expected the 8-device virtual mesh"

n_burst = int(sys.argv[1])
fails = []


def check(cond, msg):
    print(("ok  " if cond else "FAIL") + " " + msg)
    if not cond:
        fails.append(msg)


# -- 1. mesh-sharded fused train step vs single device ----------------------
import numpy as np
from znicz_tpu import prng
from znicz_tpu.backends import Device
from znicz_tpu.config import root
from znicz_tpu.models import mnist

root.mnist.synthetic.update({"n_train": 400, "n_valid": 100,
                             "n_test": 100, "noise": 0.35})


def train(mesh_shape):
    prng.seed_all(1234)
    wf = mnist.MnistWorkflow()
    wf.initialize(device=Device.create("xla"))
    tr = wf.train(fused=True, mesh_shape=mesh_shape, max_epochs=2)
    return wf, tr


wf1, _ = train(None)
wf8, tr8 = train((4, 2))
for m1, m8 in zip(wf1.decision.epoch_metrics,
                  wf8.decision.epoch_metrics):
    check(abs(m1["train_loss"] - m8["train_loss"])
          <= 1e-5 * abs(m1["train_loss"]),
          f"epoch {m1['epoch']}: 4x2 train_loss {m8['train_loss']:.6f} "
          f"matches single-device {m1['train_loss']:.6f}")
w8 = tr8.params[0][0]
check(len(w8.sharding.device_set) == 8,
      "fused params laid out over all 8 devices")
check(np.allclose(wf8.forwards[0].weights.mem,
                  wf1.forwards[0].weights.mem, rtol=1e-4, atol=1e-5),
      "written-back weights match single-device within BASELINE tol")

# -- 2. replicated + tensor-parallel serve burst ----------------------------
with tempfile.TemporaryDirectory(prefix="znicz_spmd_smoke_") as tmp:
    model = os.path.join(tmp, "demo.znn")
    from znicz_tpu.resilience.chaos import _write_demo_znn
    _write_demo_znn(model)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    proc = subprocess.Popen(
        [sys.executable, "-m", "znicz_tpu", "serve", "--model", model,
         "--port", str(port), "--max-wait-ms", "1",
         "--replicas", "2", "--tp", "2", "--warmup-shape", "4",
         "--compile-cache-dir", os.path.join(tmp, "xla-cache")],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    url = f"http://127.0.0.1:{port}/"
    try:
        for _ in range(240):                    # wait for the listener
            try:
                urllib.request.urlopen(url + "healthz", timeout=2)
                break
            except Exception:
                if proc.poll() is not None:
                    out = proc.stdout.read().decode(errors="replace")
                    sys.exit(f"serve exited rc={proc.returncode}:\n"
                             + out[-2000:])
                time.sleep(0.5)
        else:
            sys.exit("serve never answered /healthz")

        codes, lock = [], threading.Lock()

        def hit(i):
            req = urllib.request.Request(
                url + "predict",
                json.dumps({"inputs": [[0.1, -0.2, 0.3, 0.4]]
                            * (1 + i % 4)}).encode(),
                {"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=60) as r:
                    code = r.status
            except urllib.error.HTTPError as e:
                code = e.code
            except Exception as e:
                code = repr(e)
            with lock:
                codes.append(code)

        threads = [threading.Thread(target=hit, args=(i,))
                   for i in range(n_burst)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        check(len(codes) == n_burst and set(codes) == {200},
              f"burst of {n_burst} concurrent predicts -> all 200 "
              f"(got {sorted(set(codes))})")

        health = json.loads(urllib.request.urlopen(
            url + "healthz", timeout=10).read())
        check(health.get("mesh") == "1x2",
              f"healthz reports the 1x2 serving mesh "
              f"(got {health.get('mesh')!r})")
        reps = health.get("replicas") or []
        check(len(reps) == 2
              and all(r["breaker"] == "closed" for r in reps),
              f"healthz lists 2 replicas, breakers closed ({reps})")
        page = urllib.request.urlopen(url + "statusz",
                                      timeout=10).read().decode()
        check("replicas=2" in page and "tp=2" in page,
              "/statusz carries the mesh/replica topology")
        check("compile_cache: " + os.path.join(tmp, "xla-cache")
              in page, "/statusz names the persistent compile cache")
    finally:
        proc.terminate()
        try:
            proc.wait(10)
        except subprocess.TimeoutExpired:
            proc.kill()

print()
if fails:
    print(f"SPMD SMOKE FAILED ({len(fails)}):")
    for f in fails:
        print("  - " + f)
    sys.exit(1)
print("SPMD SMOKE PASSED")
PY
