#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line.

Headline metric (BASELINE.json `metric`): **ImageNet AlexNet
images/sec/chip** — the real 227×227×3 geometry (seeded synthetic data;
ImageNet itself is unavailable in this environment, BASELINE.md
provenance note), trained through the fused TPU path (whole train step
jitted, dataset HBM-resident).

``vs_baseline`` is the speedup over the *unit-graph per-op dispatch path
on the same device* — the reference's execution model (one kernel enqueue
per unit per minibatch, Python between ops; SURVEY.md §3.1 hot-loop
note), which is the only reference-equivalent baseline measurable here
(the reference's own CUDA numbers are unrecoverable — BASELINE.md)."""

import json
import sys
import time

import numpy as np


def _build(minibatch=128, n_train=512):
    from znicz_tpu import prng
    prng.seed_all(1234)
    from znicz_tpu.backends import Device
    from znicz_tpu.config import root
    from znicz_tpu.models import alexnet

    root.alexnet.update({"minibatch_size": minibatch})
    root.alexnet.synthetic.update({"n_train": n_train, "n_valid": 0,
                                   "n_test": 0})
    wf = alexnet.AlexNetWorkflow()
    wf.initialize(device=Device.create("xla"))
    return wf


def measure_fused(wf, epochs: int = 4) -> float:
    """Images/sec of the fused whole-step path."""
    from znicz_tpu.parallel import FusedTrainer

    tr = FusedTrainer(wf)
    ld = wf.loader
    data, target = ld.original_data.devmem, ld.original_labels.devmem
    n = ld.class_lengths[2]
    idx = np.arange(ld.total_samples - n, ld.total_samples)
    batch = ld.max_minibatch_size
    # two warm epochs: the first compiles, the second recompiles once
    # more when the donated params come back with device-chosen layouts
    tr.train_epoch(data, target, idx, batch, sync=True)
    tr.train_epoch(data, target, idx, batch, sync=True)
    t0 = time.perf_counter()
    last = None
    for _ in range(epochs):
        last = tr.train_epoch(data, target, idx, batch, sync=False)
    np.asarray(last["loss"])                     # one sync at the end
    dt = time.perf_counter() - t0
    return epochs * n / dt


def measure_unit_graph(wf, ticks: int = 4) -> float:
    """Images/sec of the per-unit dispatch path (reference execution
    model) on the same device and weights."""
    wf.run(max_ticks=1)                          # compile+warm all units
    t0 = time.perf_counter()
    wf.run(max_ticks=ticks)
    dt = time.perf_counter() - t0
    return ticks * wf.loader.max_minibatch_size / dt


def main() -> None:
    wf = _build()
    fused = measure_fused(wf)
    unit_graph = measure_unit_graph(wf)
    print(json.dumps({
        "metric": "alexnet_train_images_per_sec_per_chip",
        "value": round(fused, 1),
        "unit": "images/sec",
        "vs_baseline": round(fused / unit_graph, 2),
    }))


if __name__ == "__main__":
    sys.exit(main())
