#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line on stdout, always.

Headline metric (BASELINE.json `metric`): **ImageNet AlexNet
images/sec/chip** — the real 227×227×3 geometry (seeded synthetic data;
ImageNet itself is unavailable in this environment, BASELINE.md
provenance note), trained through the fused TPU path (whole train step
jitted, dataset HBM-resident).

``vs_baseline`` is the speedup over the *unit-graph per-op dispatch path
on the same device* — the reference's execution model (one kernel enqueue
per unit per minibatch, Python between ops; SURVEY.md §3.1 hot-loop
note), which is the only reference-equivalent baseline measurable here
(the reference's own CUDA numbers are unrecoverable — BASELINE.md).

Resilience contract (VERDICT round 1, item 1): the tunneled TPU backend
can refuse to initialize transiently, so the harness (a) retries backend
bring-up with backoff, (b) falls back to a reduced-size CPU measurement
if the TPU never appears (clearly labeled via "device"/"error" fields),
and (c) traps every failure into a parseable ``{"error": ...}`` JSON line
with exit code 0 — rc=1 with a raw traceback must never happen again.

Extra modes (not used by the driver):

* ``--kernels`` — run every Pallas kernel on the current device against
  its XLA twin, assert allclose, and time both (the per-kernel table
  VERDICT item 3 asks for; results land in BASELINE.md).
* ``--config NAME`` — bench a non-flagship BASELINE config
  (cifar/autoencoder/kohonen/mnist) instead of AlexNet.
* ``serve`` / ``--serve`` — the request-path twin of the headline: a
  real ``python -m znicz_tpu serve`` subprocess under closed-loop HTTP
  load, stamping req/s/core + p50/p99 + device-ms/request transcript
  rows (rev-stamped like every other row) so the ROADMAP's
  request-path speed arc is a measured trajectory.
"""

import argparse
import json
import os
import sys
import time

import numpy as np


def _compile_class(e) -> bool:
    """Whether an exception looks like a Mosaic/XLA COMPILE failure
    (scoped-VMEM OOM, compile-helper crash) rather than a transient
    tunnel/runtime error — the two must route differently: only the
    former implicates a kernel family.  Case-insensitive: Mosaic
    spells scoped-VMEM messages 'VMEM' uppercase (ADVICE r4).

    Subtlety: the axon compile RPC's URL ends in ``/remote_compile``,
    so a mid-run tunnel FLAP (connection refused / deadline exceeded,
    with the URL embedded in the channel error) must not read as a
    compile failure — that would silently downgrade the headline's
    kernel routing over a network blip.  Explicit failure markers
    (helper exit code, VMEM/Mosaic) win over transient markers; a bare
    URL with neither stays compile-class (the round-4 failures carried
    'HTTP 500' + 'tpu_compile_helper').

    The AMBIGUOUS markers — 'resource_exhausted' (a runtime HBM OOM
    spells it identically) and 'http 500' (any proxy in the tunnel can
    emit one) — only read as compile-class WITH compile context
    (remote_compile / tpu_compile_helper / mosaic / vmem) in the same
    message; alone they stay runtime/transient (ADVICE r5)."""
    sig = str(e).lower()
    if any(m in sig for m in ("vmem", "mosaic", "tpu_compile_helper")):
        return True
    if any(m in sig for m in ("resource_exhausted", "http 500")) \
            and "remote_compile" in sig:
        return True
    if any(m in sig for m in (
            "connection refused", "connection reset", "timed out",
            "broken pipe", "deadline_exceeded", "deadline exceeded",
            "unavailable", "failed to connect", "connect failed",
            "http 502", "http 504", "bad gateway",
            "gateway timeout")):
        return False
    return "remote_compile" in sig


def _preflight_lrn_pool(result, minibatch: int = 2,
                        real_geometry: bool = False) -> None:
    """Compile-check the fused LRN+pool Mosaic kernels before they gate
    the headline number; on any lowering/runtime failure fall back to
    the split layers and say so.  (The kernels are exact-equivalence
    tested in interpret mode, but Mosaic lowering can only be proven on
    the chip.)

    With ``real_geometry`` (on-chip AlexNet runs), the check compiles
    at the REAL pair geometries incl. the headline minibatch — the
    round-4 scoped-VMEM OOM scaled with the batch block, a class a
    tiny-shape preflight cannot see (VERDICT r4 item 6).  Cost is ~nil:
    these are exactly the kernels the headline step compiles, so the
    preflight pre-pays the compile cache the run then reuses."""
    try:
        import jax.numpy as jnp
        from znicz_tpu.ops import lrn_pool, tuning
        if not tuning.use_pallas():
            return                      # XLA fallback path, nothing to prove
        if real_geometry and tuning.on_tpu():
            shapes = [(minibatch, 55, 55, 96), (minibatch, 27, 27, 256)]
        else:
            shapes = [(2, 7, 7, 8)]
        for shape in shapes:
            x = (jnp.arange(int(np.prod(shape)), dtype=jnp.float32
                            ).reshape(shape) % 251) * 0.01
            # the exact kernels the headline config compiles:
            # split-input variants with the strict-relu activation fold
            xe, xo = lrn_pool.split_cols(x)
            y, idx = lrn_pool.pallas_lrn_maxpool_split(
                xe, xo, 5, 1e-4, 0.75, 2.0, (3, 3), (2, 2), 0)
            lrn_pool.pallas_gd_lrn_maxpool_split(
                y * 0.1, idx, xe, xo, 5, 1e-4, 0.75, 2.0, (3, 3),
                (2, 2), 0, fold_act="strict_relu").block_until_ready()
            # plain-x variants (non-folded pairs dispatch these)
            y, idx = lrn_pool.pallas_lrn_maxpool(
                x, 5, 1e-4, 0.75, 2.0, (3, 3), (2, 2), 0)
            lrn_pool.pallas_gd_lrn_maxpool(
                y * 0.1, idx, x, 5, 1e-4, 0.75, 2.0, (3, 3), (2, 2), 0
            ).block_until_ready()
    except Exception as e:
        # only a compile-class failure implicates the merged kernels;
        # a transient tunnel/runtime error at these (now real) shapes
        # must not silently reroute the headline to split layers — the
        # in-run fallback ladder applies the same rule (and will catch
        # a genuine failure the preflight misclassified)
        if _compile_class(e):
            os.environ["ZNICZ_TPU_LRN_POOL"] = "split"
            _append_note(result, f"lrn_pool fused kernel preflight "
                                 f"failed ({e!r}"[:160]
                         + "); using split layers")
        else:
            _append_note(result, f"lrn_pool preflight hit a non-compile"
                                 f" error ({e!r}"[:160]
                         + "); routing unchanged")


def _preflight_mxu_kernels(result) -> None:
    """Tiny-shape check of the matmul/conv Pallas family BEFORE the
    headline run (VERDICT r3 item 4): the round-3 bf16 MXU operand cast
    (`ops/matmul._mxu_cast`) only activates on real TPU, so first chip
    contact runs otherwise-unexecuted code.  Escalation ladder on
    failure: (1) ZNICZ_TPU_MXU=f32 — disable the cast; (2)
    ZNICZ_TPU_NO_PALLAS=1 — fall back to the XLA tier entirely.  Either
    way the headline number survives, with the downgrade on record."""
    from znicz_tpu.ops import tuning
    if not tuning.use_pallas():
        return

    def family(shift: int):
        # shift nudges every dim so a retry NEVER hits the jit cache of
        # a previous attempt (the cast is baked at trace time)
        import jax
        import jax.numpy as jnp
        from znicz_tpu.ops import conv as conv_ops
        from znicz_tpu.ops import deconv as deconv_ops
        from znicz_tpu.ops import matmul
        rng = np.random.default_rng(42 + shift)

        def f32(*s):
            return jnp.asarray(rng.standard_normal(s), jnp.float32)

        s = shift
        a, b = f32(16 + s, 32), f32(32, 24)
        got = matmul.pallas_matmul(a, b)
        want = matmul.xla_matmul(a, b)
        assert np.allclose(got, want, rtol=2e-2, atol=1e-1), "matmul"
        b2 = f32(16 + s, 24)
        got = matmul.pallas_matmul_at_b(a, b2)
        want = matmul.xla_matmul(a.T, b2)
        assert np.allclose(got, want, rtol=2e-2, atol=1e-1), \
            "matmul_at_b"
        x, w = f32(2, 9 + s, 9 + s, 8), f32(3, 3, 8, 16)
        y = conv_ops.pallas_conv2d(x, w, 1, 1)
        yx = conv_ops.xla_conv2d(x, w, 1, 1)
        assert np.allclose(y, yx, rtol=2e-2, atol=1e-1), "conv2d"
        err = jnp.asarray(np.asarray(yx))
        gw = conv_ops.pallas_conv2d_grad_weights(x, err, w.shape, 1, 1)
        gwx = conv_ops.xla_conv2d_grad_weights(x, err, w.shape, 1, 1)
        assert np.allclose(gw, gwx, rtol=2e-2, atol=2e-1), "grad_w"
        gx = conv_ops.pallas_conv2d_grad_input(err, w, x.shape, 1, 1)
        gxx = conv_ops.xla_conv2d_grad_input(err, w, x.shape, 1, 1)
        assert np.allclose(gx, gxx, rtol=2e-2, atol=2e-1), "grad_x"
        xd, wd = f32(2, 5 + s, 5 + s, 8), f32(4, 4, 4, 8)
        dy = deconv_ops.pallas_deconv2d(xd, wd, 2, 1)
        dyx = deconv_ops.xla_deconv2d(xd, wd, 2, 1)
        assert np.allclose(dy, dyx, rtol=2e-2, atol=1e-1), "deconv"
        jax.block_until_ready((got, y, gw, gx, dy))

    try:
        family(0)
        return
    except Exception as e:
        os.environ["ZNICZ_TPU_MXU"] = "f32"
        _append_note(result, f"mxu-cast kernel preflight failed "
                             f"({e!r}"[:160] + "); retrying with "
                     "ZNICZ_TPU_MXU=f32")
    try:
        family(1)
        return
    except Exception as e:
        os.environ["ZNICZ_TPU_NO_PALLAS"] = "1"
        _append_note(result, f"matmul/conv Pallas preflight failed even "
                             f"with f32 operands ({e!r}"[:160] + "); "
                     "Pallas tier disabled — XLA path only")


def _emit(obj) -> int:
    print(json.dumps(obj))
    sys.stdout.flush()
    return 0


_PROBE = """
import json, sys, time
t0 = time.monotonic()
import jax, jax.numpy as jnp
d = jax.devices()[0]
jnp.zeros((8, 128)).block_until_ready()
print(json.dumps({"platform": d.platform,
                  "kind": getattr(d, "device_kind", d.platform),
                  "secs": round(time.monotonic() - t0, 1)}))
"""


def _await_backend(total_wait: float):
    """Bring up the default JAX backend, retrying with backoff.

    Returns (platform, device_kind).  The tunneled TPU plugin doesn't
    just *fail* during warm-up — ``jax.devices()`` can **hang** inside
    ``make_c_api_client`` indefinitely (observed: >400 s; this is what
    produced round 1's rc=1 BENCH capture).  A hung in-process PJRT init
    can't be interrupted, so each probe runs in a subprocess that can be
    killed on timeout; this process only touches JAX once a probe has
    confirmed the backend is healthy (by then the tunnel is warm and the
    in-process init is fast).

    Round-4 refinement: the axon plugin reaches the chip through a
    local gRPC relay; when the relay is down its port REFUSES in
    milliseconds while PJRT retries forever.  A TCP pre-check
    (znicz_tpu.tpu_liveness — no-op when no relay is configured) turns
    a dead-tunnel wait from N×180 s hangs into a 10 s poll loop — and
    catches a mid-wait tunnel restoration almost immediately."""
    import subprocess

    from znicz_tpu.tpu_liveness import relay_endpoint, relay_ok

    deadline = time.monotonic() + total_wait
    delay, last = 5.0, "no probe ran"
    while True:
        left = deadline - time.monotonic()
        if left <= 0:
            raise RuntimeError(f"backend not up after {total_wait:.0f}s: "
                               f"{last}")
        if not relay_ok():
            last = ("relay port %s:%d refused (tunnel down)"
                    % relay_endpoint())
            time.sleep(min(10.0, max(0.0,
                                     deadline - time.monotonic())))
            continue
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE], capture_output=True,
                text=True, timeout=min(max(left, 10.0), 180.0))
            if proc.returncode == 0 and proc.stdout.strip():
                # scan for the probe's JSON among any plugin noise
                for line in reversed(proc.stdout.strip().splitlines()):
                    try:
                        json.loads(line)
                        break
                    except ValueError:
                        continue
                else:
                    raise ValueError("no JSON line in probe stdout")
                import jax  # safe now: tunnel verified healthy
                dev = jax.devices()[0]
                return dev.platform, getattr(dev, "device_kind",
                                             dev.platform)
            last = (proc.stderr or "").strip().splitlines()[-1:] or ["?"]
            last = last[0][-300:]
        except subprocess.TimeoutExpired:
            last = "probe hung (PJRT client init timeout)"
        except Exception as e:   # malformed stdout / transient init error:
            last = f"probe postprocessing failed: {e}"[:300]   # retry
        time.sleep(min(delay, max(0.0, deadline - time.monotonic())))
        delay = min(delay * 2.0, 60.0)


def _force_cpu():
    """Point this (not-yet-backend-initialized) process at CPU."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.extend.backend.clear_backends()
    except Exception:
        pass


#: config name → (module, workflow class, config-tree attr).
_CONFIGS = {
    "alexnet": ("alexnet", "AlexNetWorkflow", "alexnet"),
    "cifar": ("cifar", "CifarWorkflow", "cifar"),
    "mnist": ("mnist", "MnistWorkflow", "mnist"),
    "autoencoder": ("autoencoder", "MnistAEWorkflow", "mnist_ae"),
    "kohonen": ("kohonen", "KohonenWorkflow", "kohonen"),
}


def _build(config: str, minibatch, n_train):
    from znicz_tpu import prng
    prng.seed_all(1234)
    import importlib

    from znicz_tpu.backends import Device
    from znicz_tpu.config import root

    mod_name, cls, tree_name = _CONFIGS[config]
    mod = importlib.import_module(f"znicz_tpu.models.{mod_name}")
    tree = getattr(root, tree_name)
    if minibatch:
        tree.update({"minibatch_size": minibatch})
    if n_train:
        tree.synthetic.update({"n_train": n_train, "n_valid": 0,
                               "n_test": 0})
    wf = getattr(mod, cls)()
    wf.initialize(device=Device.create("xla"))
    return wf


def measure_fused(wf, epochs: int, warm: int = 2, dtype: str | None = None,
                  storage: str | None = None, mesh=None):
    """(images/sec, spec, params) of the fused whole-step path;
    ``mesh`` (a (dp, tp) shape for parallel.mesh.resolve_mesh) lays
    the step out over the device mesh.  The returned rate is
    PER-DEVICE (aggregate / mesh size), so the ``_per_chip`` metric
    and the MFU/TFLOPs derived from it stay truthful on mesh rows —
    the sharding stamp keys pairing, it does not excuse the absolute
    number."""
    import dataclasses

    from znicz_tpu.parallel import fused, FusedTrainer
    from znicz_tpu.parallel.mesh import mesh_shape_of, resolve_mesh

    spec, params, vels = fused.extract_model(wf)
    if dtype and dtype != spec.compute_dtype:
        spec = dataclasses.replace(spec, compute_dtype=dtype)
    if storage and storage != spec.storage_dtype:
        spec = dataclasses.replace(spec, storage_dtype=storage)
    mesh = resolve_mesh(mesh)
    dp, tp = mesh_shape_of(mesh)
    n_devices = dp * tp
    tr = FusedTrainer(spec=spec, params=params, vels=vels, mesh=mesh)
    ld = wf.loader
    data = ld.original_data.devmem
    # MSE heads (autoencoder) regress on target tensors, not labels
    target = (ld.original_targets.devmem
              if getattr(wf, "loss_function", "softmax") == "mse"
              else ld.original_labels.devmem)
    n = ld.class_lengths[2]
    idx = np.arange(ld.total_samples - n, ld.total_samples)
    batch = ld.max_minibatch_size
    # two warm epochs: the first compiles, the second recompiles once
    # more when the donated params come back with device-chosen layouts
    for _ in range(warm):
        tr.train_epoch(data, target, idx, batch, sync=True)
    t0 = time.perf_counter()
    last = None
    for _ in range(epochs):
        last = tr.train_epoch(data, target, idx, batch, sync=False)
    np.asarray(last["loss"])                     # one sync at the end
    dt = time.perf_counter() - t0
    return epochs * n / dt / n_devices, spec, params


def measure_stream(wf, epochs: int, warm: int = 2,
                   dtype: str | None = None, storage: str | None = None):
    """Images/sec of the streaming fused path: the SAME model/arrays as
    measure_fused, but served from .znr shards on disk through the
    double-buffered prefetcher (VERDICT item 4 done-criterion: disk-backed
    must reach >=90% of the HBM-resident number)."""
    import dataclasses
    import shutil
    import tempfile

    from znicz_tpu.loader import RecordLoader, write_records
    from znicz_tpu.parallel import fused
    from znicz_tpu.parallel.stream import StreamTrainer
    from znicz_tpu.workflow import Workflow

    spec, params, vels = fused.extract_model(wf)
    if dtype and dtype != spec.compute_dtype:
        spec = dataclasses.replace(spec, compute_dtype=dtype)
    if storage and storage != spec.storage_dtype:
        spec = dataclasses.replace(spec, storage_dtype=storage)
    ld = wf.loader
    n = ld.class_lengths[2]
    data = np.asarray(ld.original_data.mem)
    # MSE configs: reconstruct-the-input (AE contract — label block
    # unused, its IO skipped) vs distinct targets (denoising-style —
    # targets ride the shards' label block); mirror of the
    # run_fused auto-detection so resident and stream regress on the
    # SAME target tensor
    mse_target = "input"
    label_block = np.asarray(ld.original_labels.mem)
    if getattr(wf, "loss_function", "softmax") == "mse":
        targets = np.asarray(ld.original_targets.mem)
        if not np.array_equal(targets, data):
            mse_target = "labels"
            label_block = targets
    tmp = tempfile.mkdtemp(prefix="znicz_bench_znr_")
    try:
        paths = write_records(
            tmp + "/train.znr", data, label_block,
            shard_size=max(64, n // 4))
        sld = RecordLoader(Workflow(name="bench_stream"),
                           train_paths=paths,
                           minibatch_size=ld.max_minibatch_size)
        from znicz_tpu.backends import NumpyDevice
        sld.initialize(NumpyDevice())
        tr = StreamTrainer(spec=spec, params=params, vels=vels,
                           loader=sld, mse_target=mse_target)
        idx = np.arange(ld.total_samples - n, ld.total_samples)
        batch = ld.max_minibatch_size
        for _ in range(warm):
            tr.train_epoch(None, None, idx, batch, sync=True)
        t0 = time.perf_counter()
        last = None
        for _ in range(epochs):
            last = tr.train_epoch(None, None, idx, batch, sync=False)
        np.asarray(last["loss"])
        dt = time.perf_counter() - t0
        return epochs * n / dt
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def measure_augmented(spec, params, epochs: int, warm: int = 2,
                      decode: int = 256, crop: int = 227,
                      n_train: int = 512, batch: int = 128):
    """Images/sec of the resident fused path WITH on-device
    augmentation (RandomCropFlip.device_apply inside the scan): data
    lives at decode size in HBM, random crop+mirror to the net's input
    size rides the jitted step — the ImageNet-realistic variant of the
    headline number."""
    import jax.numpy as jnp

    from znicz_tpu import prng
    from znicz_tpu.loader import RandomCropFlip
    from znicz_tpu.parallel import FusedTrainer

    gen = prng.get("bench_augment")
    data = jnp.asarray(gen.normal(0.0, 1.0, (n_train, decode, decode,
                                             3)).astype(np.float32))
    labels = jnp.asarray(gen.randint(0, 1000, n_train).astype(np.int32))
    vels = [(np.zeros_like(w) if w is not None else None,
             np.zeros_like(b) if b is not None else None)
            for w, b in params]
    tr = FusedTrainer(spec=spec, params=params, vels=vels,
                      augment=RandomCropFlip((crop, crop), seed=1234))
    idx = np.arange(n_train)
    for _ in range(warm):
        tr.train_epoch(data, labels, idx, batch, sync=True)
    t0 = time.perf_counter()
    last = None
    for _ in range(epochs):
        last = tr.train_epoch(data, labels, idx, batch, sync=False)
    np.asarray(last["loss"])
    dt = time.perf_counter() - t0
    return epochs * n_train / dt


def bench_loader(args) -> int:
    """``--loader``: disk→gather→(augment)→host-batch throughput of the
    .znr pipeline with NO device in the loop — quantifies whether the
    data plane can sustain the chip's demand (the headline 3340 img/s
    at 227×227×3 implies ~1.9 GB/s of delivered pixels; VERDICT r2
    item 4).  Writes an AlexNet-geometry dataset to a temp dir, then
    drives the BatchPrefetcher for full epochs at several decode worker
    counts, reporting img/s and GB/s per count."""
    import shutil
    import tempfile

    from znicz_tpu.loader import RandomCropFlip
    from znicz_tpu.loader.records import write_records
    from znicz_tpu.loader.streaming import BatchPrefetcher, RecordLoader
    from znicz_tpu.workflow import Workflow

    result = {"metric": "alexnet_loader_images_per_sec", "value": None,
              "unit": "images/sec", "vs_baseline": None}
    try:
        # the loader bench measures the HOST pipeline; keep the hung
        # tunnel out of the loop entirely (device_put goes to CPU)
        _force_cpu()
        import jax
        result["device"] = "host (%s)" % jax.devices()[0].platform
        n, size = args.n_train, 227 + 29 if args.augment else 227
        rng = np.random.default_rng(5)
        data = rng.standard_normal((n, size, size, 3)).astype(np.float32)
        labels = rng.integers(0, 1000, n).astype(np.int32)
        row_gb = data.nbytes / n / 1e9
        tmp = tempfile.mkdtemp(prefix="znicz_bench_loader_")
        try:
            paths = write_records(tmp + "/ds.znr", data, labels,
                                  shard_size=max(64, n // 4))
            aug = (RandomCropFlip((227, 227), seed=7)
                   if args.augment else None)
            rows, fetch_rows = {}, {}
            for workers in (1, 2, 4, 8):
                os.environ["ZNICZ_TPU_IO_WORKERS"] = str(workers)
                sld = RecordLoader(Workflow(name="ldbench"),
                                   train_paths=paths,
                                   minibatch_size=args.minibatch,
                                   augment=aug)
                from znicz_tpu.backends import NumpyDevice
                sld.initialize(NumpyDevice())
                mb = args.minibatch
                steps = n // mb              # whole minibatches only
                mat = np.arange(steps * mb).reshape(steps, mb)
                for _ in range(getattr(args, "warm", 2)):  # warm the page
                    for x, t in BatchPrefetcher(sld, mat, epoch=0):
                        pass                                # cache + pool
                t0 = time.perf_counter()
                count = 0
                for ep in range(args.epochs):
                    for x, t in BatchPrefetcher(sld, mat, epoch=ep):
                        count += len(x)
                dt = time.perf_counter() - t0
                rows[workers] = round(count / dt, 1)
                # disk→host-batch alone (no device transfer): the
                # number that bounds what an overlapped DMA can be fed
                t0 = time.perf_counter()
                for ep in range(args.epochs):
                    for row in mat:
                        sld.fetch(row, epoch=ep)
                fetch_rows[workers] = round(
                    args.epochs * steps * mb
                    / (time.perf_counter() - t0), 1)
            result["rows_by_workers"] = rows
            result["fetch_by_workers"] = fetch_rows
            result["fetch_value"] = max(fetch_rows.values())
            if aug is not None:
                # device-augment streaming (StreamTrainer
                # device_augment=True) ships RAW decode-size rows; its
                # host-side bound is the un-augmented gather
                t0 = time.perf_counter()
                for ep in range(args.epochs):
                    for row in mat:
                        sld.read_batch(row)
                result["raw_fetch_value"] = round(
                    args.epochs * mat.size
                    / (time.perf_counter() - t0), 1)
            best = max(rows.values())
            result["value"] = best
            result["gb_per_sec"] = round(best * row_gb, 2)
            result["augment"] = bool(args.augment)
            # demand side: BASELINE headline img/s the chip consumes
            result["chip_demand_img_per_sec"] = 3340
            result["feeds_chip"] = bool(best >= 3340)
        finally:
            os.environ.pop("ZNICZ_TPU_IO_WORKERS", None)
            shutil.rmtree(tmp, ignore_errors=True)
    except Exception as e:
        result.setdefault("error", "")
        result["error"] = (result["error"]
                           + f" loader bench failed: {e!r}").strip()[:600]
    return _emit(result)


def _serve_row(latencies_ms, codes, duration_s, cores,
               device_ms_total) -> dict:
    """The serve-mode transcript row's measured core (pure function —
    tests pin the schema without booting a server).  ``codes`` is a
    {status: count} map over every answer; throughput counts 200s only
    (a 429 storm must not inflate req/s), latency quantiles cover every
    answered request (a refusal's latency is real client experience).

    ``req_per_sec_per_core`` divides by the host's core count — the
    cross-machine-comparable figure the ROADMAP's request-path arc
    tracks, exactly like images/sec/chip on the training side."""
    n = sum(codes.values())
    n_ok = codes.get(200, 0)
    lat = sorted(latencies_ms)
    dur = max(1e-9, float(duration_s))
    cores = max(1, int(cores))
    row = {"requests": int(n), "ok": int(n_ok),
           "codes": {str(k): int(v) for k, v in sorted(codes.items())},
           "duration_s": round(dur, 3), "cores": cores,
           "req_per_sec": round(n_ok / dur, 2),
           "req_per_sec_per_core": round(n_ok / dur / cores, 3),
           "device_ms_total": round(float(device_ms_total), 1),
           "device_ms_per_request": (
               round(float(device_ms_total) / n_ok, 3) if n_ok
               else None)}
    if lat:
        row["p50_ms"] = round(lat[len(lat) // 2], 3)
        row["p99_ms"] = round(
            lat[min(len(lat) - 1, int(len(lat) * 0.99))], 3)
    else:
        row["p50_ms"] = row["p99_ms"] = None
    return row


def bench_serve(args) -> int:
    """``bench.py serve`` (or ``--serve``): drive a REAL
    ``python -m znicz_tpu serve`` process and stamp a rev-stamped
    transcript row with req/s/core, p50/p99 and device-ms/request —
    the request-path speed arc measured exactly like the on-chip one
    (ROADMAP "raw request-path speed").  The server is a subprocess
    (its threads, signal handling and JSON parse costs are all IN the
    measurement — an in-process shortcut would flatter the number);
    the client side is N threads of closed-loop traffic."""
    import collections
    import shutil
    import signal
    import socket
    import subprocess
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    n_fleet = max(0, getattr(args, "fleet", 0))
    place = bool(getattr(args, "placement", False))
    ext_urls = [u if u.endswith("/") else u + "/"
                for u in (getattr(args, "router_url", None) or [])]
    result = {"metric": "serve_requests_per_sec_per_core",
              "value": None, "unit": "req/s/core",
              "vs_baseline": None}
    tmp = tempfile.mkdtemp(prefix="znicz_bench_serve_")
    proc = None
    fleet_procs = []
    backend_urls = []
    if place and not n_fleet:
        result["error"] = "--placement needs --fleet N (it shards a " \
                          "zoo over a fleet)"
        return _emit(result)
    if ext_urls and (n_fleet or place):
        result["error"] = "--router-url drives an EXISTING fleet; " \
                          "it excludes --fleet/--placement"
        return _emit(result)
    try:
        model = args.serve_model
        width = args.serve_width
        if ext_urls:
            # external mode boots nothing — the payload just has to
            # match the EXISTING servers' model (demo width default)
            width = width or 4
        elif model is None:
            from znicz_tpu.resilience.chaos import _write_demo_znn
            model = os.path.join(tmp, "demo.znn")
            width = 4
            _write_demo_znn(model)

        def free_port() -> int:
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        zoo_dir = os.path.join(tmp, "zoo")
        if place:
            # placement mode shards a multi-tenant zoo, not N copies
            # of one model — that IS the footprint being measured
            from znicz_tpu.serving import zoo as zoo_mod
            zoo_mod.make_demo_zoo(zoo_dir)

        def boot_serve(serve_port: int) -> subprocess.Popen:
            if place:
                return subprocess.Popen(
                    [sys.executable, "-m", "znicz_tpu", "serve",
                     "--zoo", zoo_dir, "--port", str(serve_port),
                     "--max-wait-ms", "1"],
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            return subprocess.Popen(
                [sys.executable, "-m", "znicz_tpu", "serve",
                 "--model", model, "--port", str(serve_port),
                 "--max-wait-ms", "1", "--warmup-shape", str(width)]
                # repeat traffic only pays off with the response cache
                # on; a pure-unique run serves WITHOUT memoization so
                # the two trajectories measure different levers
                + (["--memoize", "4096"]
                   if args.repeat_fraction > 0 else []),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

        def wait_health(wait_url: str, wait_proc,
                        what: str) -> dict | None:
            for _ in range(240):
                try:
                    with urllib.request.urlopen(wait_url + "healthz",
                                                timeout=2) as r:
                        return json.loads(r.read())
                except Exception:
                    if wait_proc.poll() is not None:
                        out = wait_proc.stdout.read().decode(
                            errors="replace")
                        result["error"] = (
                            f"{what} exited "
                            f"rc={wait_proc.returncode}: " + out[-400:])
                        return None
                    time.sleep(0.5)
            result["error"] = f"{what} never answered /healthz"
            return None

        if ext_urls:
            # external mode: drive EXISTING router(s) instead of
            # booting a fleet here — several urls name an HA pair
            # (primary + hot standbys) and the clients fail over
            # between them on transport error (docs/fleet.md "Router
            # high availability")
            url = None
            health = None
            deadline = time.monotonic() + 30
            while health is None and time.monotonic() < deadline:
                for u in ext_urls:
                    try:
                        with urllib.request.urlopen(u + "healthz",
                                                    timeout=2) as r:
                            health = json.loads(r.read())
                            url = u
                            break
                    except Exception:
                        continue
                if health is None:
                    time.sleep(0.5)
            if health is None:
                result["error"] = ("no router of "
                                   f"{', '.join(ext_urls)} answered "
                                   "/healthz")
                return _emit(result)
            # put the answering router first so the warm lap and the
            # clients start against a live frontend
            ext_urls = [url] + [u for u in ext_urls if u != url]
        elif n_fleet:
            # fleet mode: N serve backends behind a REAL route
            # process — the router's forwarding overhead is IN the
            # measurement, which is the point (the fleetxN trajectory
            # prices the fabric against the single-process rows)
            ports = [free_port() for _ in range(n_fleet)]
            port = free_port()
            backend_urls = [f"http://127.0.0.1:{pt}/" for pt in ports]
            fleet_procs = [boot_serve(pt) for pt in ports]
            health = None
            for burl, bproc in zip(backend_urls, fleet_procs):
                health = wait_health(burl, bproc, "fleet backend")
                if health is None:
                    return _emit(result)
            proc = subprocess.Popen(
                [sys.executable, "-m", "znicz_tpu", "route",
                 "--port", str(port)]
                + (["--placement", "1",
                    "--probe-interval-s", "0.3"] if place else [])
                + [f for i, u in enumerate(backend_urls)
                   for f in ("--backend", f"{u},name=b{i}")],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            url = f"http://127.0.0.1:{port}/"
            if wait_health(url, proc, "route") is None:
                return _emit(result)
            if place:
                # measure the PLACED steady state, not the discovery
                # transient: wait for the map to cover the zoo
                from znicz_tpu.serving.zoo import DEMO_FAMILIES
                for _ in range(80):
                    h = wait_health(url, proc, "route")
                    amap = ((h or {}).get("placement") or {}) \
                        .get("assignments") or {}
                    if set(amap) >= set(DEMO_FAMILIES):
                        break
                    time.sleep(0.25)
                else:
                    result["error"] = ("placement never covered the "
                                       "demo zoo")
                    return _emit(result)
        else:
            port = free_port()
            proc = boot_serve(port)
            url = f"http://127.0.0.1:{port}/"
            health = wait_health(url, proc, "serve")
            if health is None:
                return _emit(result)
        import http.client

        import numpy as np
        from znicz_tpu.serving import wire as wire_mod

        rows = max(1, args.serve_rows)
        base = np.full((rows, width), 0.1, dtype=np.float32)
        binary = args.payload == "binary"
        headers = ({"Content-Type": wire_mod.CONTENT_TYPE,
                    "Accept": wire_mod.CONTENT_TYPE} if binary
                   else {"Content-Type": "application/json"})
        tenants: list = []
        tenant_bodies: dict = {}
        if place:
            # cycle the zoo's tenants: placement routing (X-Model →
            # the tenant's placed backend) is the path under test
            from znicz_tpu.serving.zoo import DEMO_SHAPES
            tenants = sorted(DEMO_SHAPES)
            for name in tenants:
                tx = np.full((rows, DEMO_SHAPES[name]), 0.1,
                             dtype=np.float32)
                tenant_bodies[name] = (
                    wire_mod.encode_tensor(tx) if binary
                    else json.dumps({"inputs": tx.tolist()}).encode())

        def body_for(i: int) -> bytes:
            # i < 0 = the FIXED repeat payload; unique bodies perturb
            # one element deterministically (no RNG on a bench path)
            x = base
            if i >= 0:
                x = base.copy()
                x[0, 0] = 0.1 + (i % 100003) * 1e-4
            if binary:
                return wire_mod.encode_tensor(x)
            return json.dumps({"inputs": x.tolist()}).encode()

        fixed_body = body_for(-1)
        repeat_pct = int(round(args.repeat_fraction * 100))
        n_clients = max(1, args.serve_clients)
        traced = bool(getattr(args, "trace_breakdown", False))
        if traced:
            from znicz_tpu.telemetry import tracestore as ts_mod
            from znicz_tpu.telemetry import tracing as tracing_mod
        trace_mu = threading.Lock()
        trace_collect = threading.Event()
        stage_samples: dict = collections.defaultdict(list)
        trace_pairs: list = []       # (e2e_ms, sum-of-stages_ms)

        def _note_trace(tr, resp, data, e2e_ms):
            # the stage split comes back in-band: router-assembled
            # ("stages" present) in fleet mode, or the single server's
            # raw span summary — assembled locally with pick=0 and
            # the measured wall as the forward envelope — otherwise.
            # A spilled wire trailer beats the header when present.
            raw = resp.getheader(ts_mod.SPANS_HEADER)
            summary = ts_mod.decode_summary(raw)
            if binary:
                _clean, trailer = wire_mod.split_trailer(data)
                if trailer is not None:
                    summary = ts_mod.decode_summary(trailer)
            if summary is None:
                return
            stages = summary.get("stages")
            if isinstance(stages, dict):
                # router-assembled split: the residual between the
                # client's wall and the router's measured total is the
                # client<->router network leg — fold it into net.hop
                # so the seven stages cover the FULL e2e path
                rt = summary.get("total_ms")
                if isinstance(rt, (int, float)):
                    residual = max(0.0, e2e_ms - float(rt))
                    stages = dict(stages)
                    stages["net.hop"] = round(
                        float(stages.get("net.hop") or 0.0)
                        + residual, 3)
            else:
                stages = ts_mod.assemble(
                    trace_id=tr.trace_id, request_id="",
                    model="default", backend="local", outcome="ok",
                    total_ms=e2e_ms, pick_ms=0.0, forward_ms=e2e_ms,
                    summary=summary,
                    started_at=time.time())["stages"]
            present = {k: float(v) for k, v in stages.items()
                       if v is not None}
            if not present:
                return
            with trace_mu:
                for name, ms in present.items():
                    stage_samples[name].append(ms)
                trace_pairs.append((e2e_ms, sum(present.values())))

        def post_conn(conn, body, hdrs=None):
            hh = hdrs if hdrs is not None else headers
            tr = None
            if traced:
                # every driven request carries its own root context —
                # the breakdown wants the full population, not the
                # router's head-sampled fraction
                tr = tracing_mod.TraceContext(
                    tracing_mod.new_trace_id(),
                    tracing_mod.new_span_id())
                hh = dict(hh)
                hh[ts_mod.TRACE_HEADER] = \
                    tracing_mod.format_traceparent(tr)
            t_req = time.monotonic()
            conn.request("POST", "/predict", body, hh)
            r = conn.getresponse()
            data = r.read()
            if traced and trace_collect.is_set() and r.status == 200:
                try:
                    _note_trace(tr, r, data,
                                (time.monotonic() - t_req) * 1e3)
                except Exception:
                    pass          # a torn summary never fails a bench
            return r.status

        if ext_urls:
            from urllib.parse import urlsplit
            targets = [((urlsplit(u).hostname or "127.0.0.1"),
                        (urlsplit(u).port or 80)) for u in ext_urls]
        else:
            targets = [("127.0.0.1", port)]
        warm = http.client.HTTPConnection(*targets[0], timeout=60)
        if place:                     # one warm lap per tenant
            for name in tenants:
                post_conn(warm, tenant_bodies[name],
                          dict(headers, **{"X-Model": name}))
        else:
            post_conn(warm, fixed_body)
        warm.close()
        trace_collect.set()           # warm-lap compiles stay out
        answers = []                  # (latency_ms, code)
        mu = threading.Lock()
        stop = threading.Event()

        def client(ci: int):
            # one persistent connection per closed-loop client — the
            # HTTP/1.1 keep-alive contract is part of what's measured;
            # a dropped connection re-opens (that request's latency
            # carries the reconnect, like a real client's would).
            # With several --router-url targets (an HA pair) a
            # transport error ALSO rotates to the next router; an HTTP
            # answer never does — a 503 + Retry-After refusal during a
            # takeover is an answer, and shows up in the codes map
            active = 0

            def connect():
                return http.client.HTTPConnection(
                    *targets[active % len(targets)], timeout=30)

            conn = connect()
            i = ci
            while not stop.is_set():
                if place:
                    name = tenants[i % len(tenants)]
                    body = tenant_bodies[name]
                    hdrs = dict(headers, **{"X-Model": name})
                else:
                    body = (fixed_body if (i % 100) < repeat_pct
                            else body_for(i))
                    hdrs = None
                t0 = time.monotonic()
                try:
                    code = post_conn(conn, body, hdrs)
                except Exception:
                    conn.close()
                    active += 1
                    conn = connect()
                    code = -1
                dt_ms = (time.monotonic() - t0) * 1e3
                with mu:
                    answers.append((dt_ms, code))
                i += n_clients
            conn.close()

        def device_ms_now() -> float:
            # fleet mode: the chip time lives in the BACKENDS — sum
            # their ledgers (the router itself runs no device code);
            # a zoo backend's ledger is per-tenant, so placement mode
            # sums the healthz model rows instead of the engine total
            if ext_urls:
                # external routers: the backends aren't ours to
                # scrape — device-ms is reported as 0, not guessed
                return 0.0
            if place:
                return sum(_scrape_zoo_device_ms(u)
                           for u in backend_urls)
            if n_fleet:
                return sum(_scrape_device_ms(u) for u in backend_urls)
            return _scrape_device_ms(url)

        dev0 = device_ms_now()
        threads = [threading.Thread(target=client, args=(ci,),
                                    daemon=True)
                   for ci in range(n_clients)]
        t_start = time.monotonic()
        for t in threads:
            t.start()
        stop.wait(args.serve_duration_s)
        stop.set()
        for t in threads:
            t.join(30.0)
        duration_s = time.monotonic() - t_start
        device_ms = device_ms_now() - dev0
        fleet_resident = zoo_total = None
        if place:
            # the footprint claim, measured at the end of the burst:
            # fleet resident bytes vs one zoo's total weight bytes
            fleet_resident = 0
            zoo_total = 0
            for u in backend_urls:
                try:
                    with urllib.request.urlopen(u + "healthz",
                                                timeout=10) as r:
                        snap = json.loads(r.read())
                except Exception:
                    continue
                fleet_resident += int(snap.get("resident_bytes") or 0)
                zoo_total = max(zoo_total, sum(
                    int(row.get("weight_bytes") or 0)
                    for row in snap.get("models") or []))
        own_procs = ([proc] if proc is not None else []) + fleet_procs
        for p_ in own_procs:
            p_.send_signal(signal.SIGINT)
        for p_ in own_procs:
            try:
                p_.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p_.kill()
        proc = None
        fleet_procs = []
        codes = collections.Counter(c for _l, c in answers)
        # quantiles cover ANSWERED requests only (the _serve_row
        # contract): a hung/dropped request's "latency" is just the
        # client timeout and would corrupt p99 for the whole row — it
        # is reported through the codes map and the error note instead
        row = _serve_row([latency for latency, c in answers if c != -1],
                         codes, duration_s, os.cpu_count() or 1,
                         device_ms)
        result.update(row)
        result["value"] = row["req_per_sec_per_core"]
        result["device"] = (f"host serve "
                            f"[{health.get('backend', '?')}]")
        result["clients"] = args.serve_clients
        result["rows_per_request"] = max(1, args.serve_rows)
        # wire-format + repeat-mix provenance: trajectories only pair
        # like-for-like when the row says WHICH path was driven
        result["payload"] = args.payload
        result["repeat_fraction"] = args.repeat_fraction
        if traced:
            # the p99 decomposition: per-stage quantiles over every
            # assembled trace, plus the honesty check — the stage sum
            # must track the measured e2e wall (the acceptance gate
            # wants the medians within ~10%)
            def _q(sorted_vals, frac):
                return round(sorted_vals[min(len(sorted_vals) - 1,
                                             int(len(sorted_vals)
                                                 * frac))], 3)
            br: dict = {}
            for name in ts_mod.STAGES:
                vals = sorted(stage_samples.get(name) or [])
                if vals:
                    br[name] = {"p50_ms": _q(vals, 0.5),
                                "p99_ms": _q(vals, 0.99)}
            sums = sorted(s for _e, s in trace_pairs)
            e2es = sorted(e for e, _s in trace_pairs)
            result["trace_breakdown"] = {
                "traces": len(trace_pairs),
                "stages": br,
                "stage_sum_p50_ms": _q(sums, 0.5) if sums else None,
                "e2e_p50_ms": _q(e2es, 0.5) if e2es else None,
                "stage_sum_p99_ms": _q(sums, 0.99) if sums else None,
                "e2e_p99_ms": _q(e2es, 0.99) if e2es else None,
                "sum_over_e2e": (
                    round(_q(sums, 0.5) / max(1e-9, _q(e2es, 0.5)), 3)
                    if sums and e2es else None)}
        rev = _git_rev()
        if rev:
            result["rev"] = rev
        # the topology is part of a serve measurement's identity,
        # exactly like the mesh scheme on the training side: fleetxN
        # rows only pair with fleetxN rows in decide_levers
        result["sharding"] = (f"externalx{len(ext_urls)}" if ext_urls
                              else f"fleetx{n_fleet}+place" if place
                              else f"fleetx{n_fleet}" if n_fleet
                              else "1x1")
        if n_fleet:
            result["fleet"] = n_fleet
        if place:
            result["placement"] = 1     # the replication factor
            result["fleet_resident_bytes"] = fleet_resident
            result["zoo_total_bytes"] = zoo_total
        result["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime())
        if codes.get(-1):
            result.setdefault("error", "")
            result["error"] = (result["error"] + f" {codes[-1]} "
                               f"request(s) hung/dropped").strip()
    except Exception as e:
        result.setdefault("error", "")
        result["error"] = (result["error"]
                           + f" serve bench failed: {e!r}").strip()[:600]
    finally:
        for p_ in ([proc] if proc is not None else []) + fleet_procs:
            p_.kill()
        shutil.rmtree(tmp, ignore_errors=True)
    return _emit(result)


def _scrape_zoo_device_ms(url: str) -> float:
    """A multi-tenant backend's device-ms, summed over its healthz
    model rows (the per-tenant ledger; 0.0 when unreachable)."""
    import urllib.request
    try:
        with urllib.request.urlopen(url + "healthz", timeout=10) as r:
            snap = json.loads(r.read())
        return sum(float(row.get("device_ms") or 0.0)
                   for row in snap.get("models") or [])
    except Exception:
        return 0.0


def _scrape_device_ms(url: str) -> float:
    """The server's measured engine device-ms total from the JSON
    /metrics view (0.0 when unreachable — the delta then honestly
    reads as 'unmeasured', not a crash)."""
    import urllib.request
    try:
        with urllib.request.urlopen(url + "metrics", timeout=10) as r:
            m = json.loads(r.read())
        return float((m.get("engine") or {}).get("device_ms_total", 0.0))
    except Exception:
        return 0.0


def measure_unit_graph(wf, ticks: int) -> float:
    """Images/sec of the per-unit dispatch path (reference execution
    model) on the same device and weights."""
    wf.run(max_ticks=1)                          # compile+warm all units
    t0 = time.perf_counter()
    wf.run(max_ticks=ticks)
    dt = time.perf_counter() - t0
    return ticks * wf.loader.max_minibatch_size / dt


def measure_som_fused(wf, epochs: int):
    """(samples/sec, flops/sample) of the fused SOM epoch scan."""
    from znicz_tpu.loader.base import TRAIN
    from znicz_tpu.parallel.som import FusedSOMTrainer

    ld = wf.loader
    tr = FusedSOMTrainer(np.asarray(wf.forward.weights.mem),
                         wf.forward.shape, workflow=wf)
    data = ld.original_data.devmem
    perm = ld.train_permutation(ld.epoch_number)
    batch = ld.max_minibatch_size
    n = ld.class_lengths[TRAIN]
    lr, sigma = wf.trainer.schedules()
    tr.train_epoch(data, perm, batch, lr, sigma)       # compile
    tr.train_epoch(data, perm, batch, lr, sigma)
    t0 = time.perf_counter()
    for _ in range(epochs):
        tr.train_epoch(data, perm, batch, lr, sigma)
    dt = time.perf_counter() - t0
    n_neurons = int(np.prod(wf.forward.shape))
    dim = int(np.prod(ld.original_data.shape[1:]))
    return epochs * n / dt, 6.0 * n_neurons * dim


def _reduce_for_cpu(args):
    """Shrink to 'prove the path compiles and emit a labeled number':
    ticks=0 skips the per-unit dispatch pass entirely (compiling every
    unit's kernels on CPU costs minutes and the CPU ratio is meaningless
    for the TPU headline)."""
    args.minibatch, args.n_train = 4, 4
    args.epochs, args.ticks, args.warm = 1, 0, 1


def _append_note(result, note: str) -> None:
    """The ONE way a bench result accumulates advisory notes."""
    result["note"] = (result["note"] + "; " + note
                      if "note" in result else note)


def _git_rev() -> str | None:
    """Short git sha of the checkout the bench ran from, suffixed
    ``-dirty.<hash-of-diff>`` when the CODE has uncommitted edits —
    two runs straddling an uncommitted kernel tweak are NOT the same
    code, and two *different* tweaks must not share a stamp either.
    None when not a repo / no git.  Stamped into every transcript row
    so decide_levers.py can refuse to average or pair rows measured on
    different code revisions (ADVICE r5 medium: cross-revision rows
    contaminate keep/revert verdicts).

    The implementation lives in ``znicz_tpu.telemetry.buildinfo`` so
    the serving ``/metrics`` endpoint stamps the identical ``rev``
    (scraped metrics and transcript rows must attribute to the same
    build string).  The CODE-paths rule (no ``tests``: a test-only
    edit cannot change a measurement) is the shared default there."""
    from znicz_tpu.telemetry import buildinfo
    return buildinfo.git_rev(
        root=os.path.dirname(os.path.abspath(__file__)))


def _record_run_config(args, result, mesh_applies: bool = False) -> None:
    """Stamp the transcript row with what ACTUALLY ran: the active
    routing levers, the code revision, and the (possibly CPU-reduced)
    minibatch.  Callers invoke this after backend bring-up / env
    fixups, not before — a row claiming levers the run stripped, or
    the pre-reduction batch size, would mislead decide_levers.py's
    readers."""
    levers = {k: v for k, v in sorted(os.environ.items())
              if k.startswith("ZNICZ_TPU_")}
    if levers:
        result["levers"] = levers
    else:
        result.pop("levers", None)
    # the EFFECTIVE routing (env + defaults resolved): decide_levers.py
    # compares configurations by this field, so transcript rows keep
    # their meaning across default flips (round 5 flipped fused2 on,
    # which silently re-aimed every pre-flip "no levers" row)
    from znicz_tpu.ops import tuning
    result["resolved"] = tuning.resolved_routing()
    rev = _git_rev()
    if rev:
        result["rev"] = rev
    else:
        # an unstamped row pools with pre-round-6 legacy history in
        # decide_levers — that must never happen silently
        print("warning: no git revision available; transcript row is "
              "unstamped and will pair with legacy (rev-less) rows",
              file=sys.stderr)
    # the sharding scheme is part of a measurement's identity exactly
    # like the minibatch: a "4x2"-mesh row and a single-device "1x1"
    # row measure different programs, so decide_levers must only pair
    # like-for-like (its headline key includes this field).  Only the
    # training path actually lays work over the mesh (mesh_applies);
    # the kernel/ablate/loader modes measure single-device regardless
    # of the flag and must say so
    if mesh_applies and getattr(args, "mesh", None):
        from znicz_tpu.parallel.mesh import parse_mesh_arg
        dp, tp = parse_mesh_arg(args.mesh)
        result["sharding"] = f"{dp}x{tp}"
    else:
        result["sharding"] = "1x1"
        if getattr(args, "mesh", None) and not mesh_applies:
            _append_note(result, "--mesh does not apply to this bench "
                                 "mode; measured single-device")
    result["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    result["minibatch"] = args.minibatch


def _last_onchip_row():
    """Freshest on-chip headline row from the burn transcripts
    (backlog_r*.jsonl), or None.  VERDICT r4 item 3: when the driver
    captures bench.py during a tunnel outage, the cpu-fallback JSON
    must still carry the round's on-chip story — in a clearly-labeled
    provenance field, NEVER in device/value."""
    import calendar
    import glob

    def _epoch(ts, fallback):
        # rows mix formats: post-round-5 rows carry an ISO `ts`
        # string, round-4 rows only their file's mtime — the sort key
        # must be one comparable type (float seconds) or the first
        # mixed-transcript scan raises TypeError
        try:
            return calendar.timegm(time.strptime(ts,
                                                 "%Y-%m-%dT%H:%M:%SZ"))
        except (TypeError, ValueError):
            return fallback
    best = None                     # ((epoch_s, line_no), row, path)
    for path in sorted(glob.glob(
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "backlog_r*.jsonl"))):
        try:
            mtime = os.path.getmtime(path)
            with open(path) as f:
                for i, line in enumerate(f):
                    try:
                        row = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    # exact headline metric only: a newer on-chip
                    # mnist/cifar row must not impersonate the
                    # flagship number this field exists to preserve
                    if (row.get("value") is None
                            or row.get("metric")
                            != "alexnet_train_images_per_sec_per_chip"
                            or "cpu" in str(row.get("device", "")
                                            ).lower()):
                        continue
                    key = (_epoch(row.get("ts"), mtime), i)
                    if best is None or key > best[0]:
                        best = (key, row, path)
        except OSError:
            continue
    if best is None:
        return None
    _, row, path = best
    keep = {k: row[k] for k in ("metric", "value", "unit", "device",
                                "minibatch", "mfu", "tflops_per_sec",
                                "levers", "resolved", "rev", "ts")
            if k in row}
    keep["transcript"] = os.path.basename(path)
    if "ts" not in keep:            # pre-round-5 rows carry no ts
        keep["measured_at"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ",
            time.gmtime(os.path.getmtime(path))) + " (file mtime)"
    return keep


def _attach_last_onchip(result) -> None:
    try:
        row = _last_onchip_row()
    except Exception:
        return
    if row is not None:
        result["last_onchip"] = row
        _append_note(result,
                     "device is a CPU fallback; last_onchip carries the "
                     "freshest real-TPU measurement from the burn "
                     "transcripts (provenance field, not this run)")


def _bring_up(args, result, reduce_on_cpu: bool = True):
    """Shared backend bring-up: await the TPU, else labeled CPU
    fallback.  Mutates ``result`` (device/note/error fields) and
    returns the platform string, or None when even the fallback failed
    (caller emits and exits) — the single copy of the resilience
    contract every bench mode relies on (VERDICT r1 item 1)."""
    try:
        platform, kind = _await_backend(args.backend_wait)
        result["device"] = kind
        if platform == "cpu":
            # jax silently defaulted to host CPU (no TPU registered at
            # all): keep the run small and say so — full-size epochs on
            # CPU take hours and aren't the headline metric.
            _append_note(result, "no TPU registered; reduced-size CPU run")
            _attach_last_onchip(result)
            if reduce_on_cpu:
                _reduce_for_cpu(args)
        return platform
    except Exception as e:
        # TPU never came up: emit a labeled reduced-size CPU number so
        # the line still parses, and carry the init error for the record.
        result["error"] = f"tpu backend init failed: {e}"[:400]
        try:
            _force_cpu()
            import jax
            dev = jax.devices()[0]   # in-process: axon never registered
            if dev.platform != "cpu":
                raise RuntimeError(f"got {dev.platform}, wanted cpu")
            kind = getattr(dev, "device_kind", "cpu")
            result["device"] = f"cpu-fallback ({kind})"
            _attach_last_onchip(result)
            if reduce_on_cpu:
                _reduce_for_cpu(args)
            return "cpu"
        except Exception as e2:
            result["error"] += f"; cpu fallback failed: {e2}"[:200]
            return None


def bench_training(args) -> int:
    result = {"metric": f"{args.config}_train_images_per_sec_per_chip",
              "value": None, "unit": "images/sec", "vs_baseline": None}
    if _bring_up(args, result) is None:
        return _emit(result)
    _preflight_lrn_pool(result, args.minibatch,
                        real_geometry=args.config == "alexnet")
    _preflight_mxu_kernels(result)
    _record_run_config(args, result, mesh_applies=True)
    try:
        from znicz_tpu.ops import flops as flops_mod

        wf = _build(args.config, args.minibatch, args.n_train)
        if args.config == "kohonen":
            # the SOM has no gradient chain; its fused path is the
            # dedicated epoch scan in parallel.som
            if result.get("sharding", "1x1") != "1x1":
                # the SOM scan has no mesh path: measured single-
                # device, and the row must say so instead of pairing
                # with genuine mesh rows
                result["sharding"] = "1x1"
                _append_note(result,
                             "--mesh is not implemented for the "
                             "kohonen SOM path; measured single-"
                             "device (sharding restamped 1x1)")
            ips, flops_img = measure_som_fused(wf, args.epochs)
            result["value"] = round(ips, 1)
            result["flops_per_image"] = flops_img
            result["tflops_per_sec"] = round(ips * flops_img / 1e12, 4)
            if args.ticks > 0:
                unit_graph = measure_unit_graph(wf, args.ticks)
                result["vs_baseline"] = round(ips / unit_graph, 2)
            return _emit(result)
        try:
            for attempt in (0, 1):
                try:
                    fused_ips, spec, params = measure_fused(
                        wf, args.epochs, getattr(args, "warm", 2),
                        dtype=args.dtype, storage=args.storage,
                        mesh=args.mesh)
                    break
                except NotImplementedError:
                    raise
                except Exception as e:
                    # a real-geometry Mosaic failure the tiny-shape
                    # preflight can't see (e.g. scoped-VMEM OOM scales
                    # with the batch block): fall back to the split
                    # pair layers and re-measure — the headline number
                    # survives with the downgrade on record.  Only worth
                    # trying when a merged pair was actually in play.
                    if attempt:
                        raise
                    # only a compile-class failure implicates the merged
                    # kernels; a transient runtime/tunnel error must not
                    # get misattributed to them (and must not publish a
                    # silently-downgraded split number)
                    if not _compile_class(e):
                        raise
                    from znicz_tpu.ops import tuning as _tuning
                    from znicz_tpu.parallel import fused as _fused
                    try:
                        merged_active = (
                            _tuning.use_pallas()
                            and _tuning.lrn_pool_merge()
                            and any(l.kind == "lrn_pool" for l in
                                    _fused.extract_model(wf)[0].layers))
                    except Exception:
                        merged_active = False
                    if not merged_active:
                        raise
                    os.environ["ZNICZ_TPU_LRN_POOL"] = "split"
                    _append_note(result,
                                 f"merged pair failed at real geometry "
                                 f"({e!r}"[:200] + "); split-layer retry")
                    wf = _build(args.config, args.minibatch, args.n_train)
                    # the row must record the levers that actually ran
                    _record_run_config(args, result, mesh_applies=True)
            result["path"] = "fused"
            result["compute_dtype"] = (args.dtype or "float32")
            if args.storage:
                result["storage_dtype"] = args.storage
        except NotImplementedError as e:
            # e.g. weight-tied Deconv: fall back to the unit-graph path
            # so the config still gets a measured number
            result["path"] = "unit_graph"
            _append_note(result, f"fused path unavailable: {e}"[:200])
            fused_ips = measure_unit_graph(wf, max(args.ticks, 1))
            spec = params = None
            if result.get("sharding", "1x1") != "1x1":
                # the unit-graph fallback ran single-device: the row
                # must not pair with genuine mesh rows in decide_levers
                result["sharding"] = "1x1"
                _append_note(result, "unit-graph fallback is single-"
                                     "device; sharding restamped 1x1")
        result["value"] = round(fused_ips, 1)
        # a mesh row records ONLY mesh measurements: the unit-graph /
        # stream / augment comparators below run meshless, and pairing
        # a meshless aggregate with a per-device mesh number (or
        # landing it in a sharding-stamped row) is exactly the
        # cross-program mixing the sharding key exists to forbid
        meshed = result.get("sharding", "1x1") != "1x1"
        if meshed and (args.ticks > 0 or args.stream or args.augment):
            _append_note(result,
                         "mesh run: the unit-graph/stream/augment "
                         "comparators are meshless and were skipped "
                         "(measure them without --mesh)")
        if spec is not None:
            fl = flops_mod.model_flops(
                spec, params, wf.loader.original_data.shape[1:])
            achieved = fused_ips * fl["train_step"] / 1e12
            result["tflops_per_sec"] = round(achieved, 2)
            result["flops_per_image"] = fl["train_step"]
            peak = flops_mod.peak_tflops(result.get("device", ""),
                                         spec.compute_dtype)
            if peak:
                result["mfu"] = round(achieved / peak, 4)
                result["peak_tflops"] = peak
            # publish the bf16-MXU-peak MFU alongside (VERDICT r2 item
            # 8): XLA runs f32 convs as bf16 MXU passes at default
            # precision, so the f32-peak number alone could read as
            # denominator-shopping
            peak_bf16 = flops_mod.peak_tflops(result.get("device", ""),
                                              "bfloat16")
            if peak_bf16:
                result["mfu_vs_bf16_peak"] = round(achieved / peak_bf16,
                                                   4)
                result["peak_tflops_bf16"] = peak_bf16
            # MSE heads stream too: StreamTrainer's mse_target="input"
            # default reconstructs x (the AE contract) and skips the
            # label block's IO entirely
            if args.stream and not meshed:
                stream_ips = measure_stream(wf, args.epochs,
                                            getattr(args, "warm", 2),
                                            dtype=args.dtype,
                                            storage=args.storage)
                result["stream_value"] = round(stream_ips, 1)
                result["stream_vs_resident"] = round(
                    stream_ips / fused_ips, 3)
            if args.augment and args.config == "alexnet" \
                    and not meshed:
                size = int(wf.loader.original_data.shape[1])
                aug_ips = measure_augmented(
                    spec, params, args.epochs,
                    getattr(args, "warm", 2),
                    decode=size + 29, crop=size,
                    n_train=args.n_train, batch=args.minibatch)
                result["augment_value"] = round(aug_ips, 1)
                result["augment_vs_plain"] = round(
                    aug_ips / fused_ips, 3)
            if args.ticks > 0 and not meshed:
                unit_graph = measure_unit_graph(wf, args.ticks)
                result["vs_baseline"] = round(fused_ips / unit_graph, 2)
        # a requested measurement must never quietly not run — covers
        # both the non-alexnet --augment case and the unit-graph
        # fallback (spec None) skipping --stream/--augment entirely
        # (the meshed skips above carry their own note)
        if args.stream and "stream_value" not in result and not meshed:
            _append_note(result, "--stream requested but not measured "
                                 "(fused path unavailable)")
        if args.augment and "augment_value" not in result \
                and not meshed:
            _append_note(result,
                         "--augment requested but not measured ("
                         + ("only implemented for the alexnet config"
                            if args.config != "alexnet"
                            else "fused path unavailable") + ")")
    except Exception as e:
        result.setdefault("error", "")
        result["error"] = (result["error"]
                           + f" measure failed: {e!r}").strip()[:600]
    return _emit(result)


# -- per-kernel Pallas-vs-XLA validation (VERDICT item 3) ------------------
def _kernel_cases():
    """[(name, pallas_thunk, xla_thunk, compare)] on bench-scale shapes."""
    import jax.numpy as jnp
    from znicz_tpu.ops import (activations, conv as conv_ops,
                               deconv as deconv_ops,
                               dropout as drop_ops,
                               elementwise, kohonen as som_ops,
                               lrn_pool as lrn_pool_ops, matmul,
                               normalization as lrn_ops,
                               softmax, update)

    rng = np.random.default_rng(1234)

    def f32(*s):
        return jnp.asarray(rng.standard_normal(s), jnp.float32)

    a, b = f32(512, 1024), f32(1024, 768)
    a2 = f32(512, 768)                       # matmul_at_b rhs
    logits = f32(1024, 1000)
    labels = jnp.asarray(rng.integers(0, 1000, size=1024), jnp.int32)
    x4 = f32(32, 28, 28, 64)
    err4 = f32(32, 28, 28, 64)
    xact = f32(1024, 4096)
    yact, eact = f32(1024, 4096), f32(1024, 4096)
    w = f32(4096, 1024)
    grad, vel = f32(4096, 1024), f32(4096, 1024)
    seed, ctrs = 1234, (7, 3, 11)
    taps = f32(9, 32 * 14 * 14, 64)          # (window taps, rows, C)
    xsom, wsom = f32(256, 784), f32(400, 784)   # 20x20 SOM on MNIST dims
    perr = f32(32 * 14 * 14, 64)
    poff = jnp.asarray(rng.integers(0, 9, size=(32 * 14 * 14, 64)),
                       jnp.int32)
    ximg, cerr = f32(16, 28, 28, 64), f32(16, 28, 28, 64)
    cw = f32(3, 3, 64, 64)
    xdec, wdec = f32(16, 14, 14, 32), f32(4, 4, 16, 32)
    hypers = jnp.asarray([0.01, 1e-4, 0.0, 0.9], jnp.float32)
    _, d_lrn = lrn_ops.xla_lrn(x4)
    xlp = f32(32, 55, 55, 96)               # AlexNet L1 LRN+pool geometry
    _, olp = lrn_pool_ops.xla_lrn_maxpool(xlp, 5, 1e-4, 0.75, 2.0,
                                          (3, 3), (2, 2), 0)
    elp = f32(*olp.shape)

    cases = [
        ("matmul", lambda: matmul.pallas_matmul(a, b),
         lambda: matmul.xla_matmul(a, b), "close"),
        # round-3 transposed-lhs weight-grad kernel: aᵀ@b without
        # materializing aᵀ in HBM (conv grad_w contracts through it)
        ("matmul_at_b", lambda: matmul.pallas_matmul_at_b(a, a2),
         lambda: matmul.xla_matmul(a.T, a2), "close"),
        ("conv",
         lambda: conv_ops.pallas_conv2d(ximg, cw, 1, 1),
         lambda: conv_ops.xla_conv2d(ximg, cw, 1, 1), "close"),
        ("softmax", lambda: softmax.pallas_softmax(logits),
         lambda: softmax.xla_softmax(logits), "close"),
        ("softmax_ce",
         lambda: softmax.pallas_softmax_ce_from_logits(logits, labels),
         lambda: softmax.xla_softmax_ce_from_logits(logits, labels),
         "close"),
        ("act_bwd_tanh",
         lambda: elementwise.pallas_act_bwd("tanh", eact, yact),
         lambda: activations.BY_NAME["tanh"].bwd(eact, yact, None, jnp),
         "close"),
        ("dropout",
         lambda: elementwise.pallas_dropout(xact, seed, ctrs, 0.4),
         lambda: xact * drop_ops.make_mask(seed, ctrs, xact.shape, 0.4,
                                           jnp), "exact"),
        ("lrn", lambda: elementwise.pallas_lrn(x4)[0],
         lambda: lrn_ops.xla_lrn(x4)[0], "close"),
        ("gd_lrn",
         lambda: elementwise.pallas_gd_lrn(err4, x4, d_lrn),
         lambda: lrn_ops.xla_gd_lrn(err4, x4, d_lrn), "close"),
        ("lrn_y", lambda: elementwise.pallas_lrn_y(x4),
         lambda: lrn_ops.xla_lrn(x4)[0], "close"),
        ("gd_lrn_x",
         lambda: elementwise.pallas_gd_lrn_x(err4, x4),
         lambda: lrn_ops.xla_gd_lrn_x(err4, x4), "close"),
        ("pool_select",
         lambda: elementwise.pallas_pool_select(taps)[0],
         lambda: jnp.max(taps, axis=0), "close"),
        ("pool_scatter",
         lambda: elementwise.pallas_pool_scatter(perr, poff, 9),
         lambda: jnp.stack([perr * (poff == t) for t in range(9)]),
         "exact"),
        ("pool_gather",
         lambda: elementwise.pallas_pool_gather(taps, poff),
         lambda: sum(taps[t] * (poff == t) for t in range(9)), "close"),
        ("conv_grad_w",
         lambda: conv_ops.pallas_conv2d_grad_weights(
             ximg, cerr, (3, 3, 64, 64), 1, 1),
         lambda: conv_ops.xla_conv2d_grad_weights(
             ximg, cerr, (3, 3, 64, 64), 1, 1), "close"),
        ("conv_grad_x",
         lambda: conv_ops.pallas_conv2d_grad_input(
             cerr, cw, ximg.shape, 1, 1),
         lambda: conv_ops.xla_conv2d_grad_input(
             cerr, cw, ximg.shape, 1, 1), "close"),
        ("deconv",
         lambda: deconv_ops.pallas_deconv2d(xdec, wdec, 2, 1),
         lambda: deconv_ops.xla_deconv2d(xdec, wdec, 2, 1), "close"),
        ("kohonen_argmin",
         lambda: som_ops.pallas_distance_argmin(xsom, wsom)[0],
         lambda: som_ops.xla_forward(xsom, wsom)[0], "exact"),
        # the round-3 fused LRN+max-pool pair, at AlexNet L1-like
        # geometry (stride-2 3x3 pool, cross-channel LRN)
        ("lrn_maxpool",
         lambda: lrn_pool_ops.pallas_lrn_maxpool(
             xlp, 5, 1e-4, 0.75, 2.0, (3, 3), (2, 2), 0)[0],
         lambda: lrn_pool_ops.xla_lrn_maxpool(
             xlp, 5, 1e-4, 0.75, 2.0, (3, 3), (2, 2), 0)[0], "close"),
        ("gd_lrn_maxpool",
         lambda: lrn_pool_ops.pallas_gd_lrn_maxpool(
             elp, olp, xlp, 5, 1e-4, 0.75, 2.0, (3, 3), (2, 2), 0),
         lambda: lrn_pool_ops.xla_gd_lrn_maxpool(
             elp, olp, xlp, 5, 1e-4, 0.75, 2.0, (3, 3), (2, 2), 0),
         "close"),
        ("sgd_update",
         lambda: update.pallas_sgd_update(w, grad, vel, hypers),
         lambda: update.xla_sgd_update(w, grad, vel, 0.01, 1e-4, 0.0,
                                       0.9), "close"),
    ]
    for act in ("tanh", "relu", "sigmoid"):
        cases.append((
            f"act_fwd_{act}",
            lambda act=act: elementwise.pallas_act_fwd(act, xact),
            lambda act=act: activations.BY_NAME[act].fwd(xact, jnp),
            "close"))
    return cases


def bench_ablate(args) -> int:
    """Layer-kind ablation of the fused step (--ablate): times the
    config's full net against variants with whole layer kinds removed,
    plus the bf16-storage variant — the reproducible source of the
    'where the time goes' table in docs/performance.md."""
    import dataclasses

    result = {"metric": f"{args.config}_ablation", "value": None,
              "unit": "ms_per_step", "vs_baseline": None}
    if args.config == "kohonen":
        # config-determined: answer before waiting out backend bring-up
        result["error"] = ("ablation needs a layer-chain config; the "
                           "SOM has a dedicated epoch scan with no "
                           "removable layer kinds")
        return _emit(result)
    if _bring_up(args, result) is None:
        return _emit(result)
    # the table owns the routing levers END TO END: an ambient
    # ZNICZ_TPU_LRN_POOL=fused2 or CONV1=s2d would otherwise leak into
    # base_spec extraction and the baseline rows, flattening every A/B
    # delta.  Strip the ambient levers BEFORE the preflights: a
    # safety fallback the preflight sets (LRN_POOL=split on a
    # compile-class failure, like NO_PALLAS in the MXU ladder) must
    # survive into the table, not be popped with the ambient values.
    saved_env = {v: os.environ.pop(v, None)
                 for v in ("ZNICZ_TPU_LRN_POOL", "ZNICZ_TPU_CONV1")}
    _preflight_lrn_pool(result, args.minibatch,
                        real_geometry=args.config == "alexnet")
    _preflight_mxu_kernels(result)
    _record_run_config(args, result)
    try:
        from znicz_tpu.parallel import fused, FusedTrainer

        wf = _build(args.config, args.minibatch, args.n_train)
        base_spec, params, vels = fused.extract_model(wf)
        ld = wf.loader
        data = ld.original_data.devmem
        target = (ld.original_targets.devmem
                  if getattr(wf, "loss_function", "softmax") == "mse"
                  else ld.original_labels.devmem)
        n = ld.class_lengths[2]
        idx = np.arange(ld.total_samples - n, ld.total_samples)
        batch = ld.max_minibatch_size
        import jax

        def time_spec(spec, keep=None, ps=None, vs=None):
            ps = params if ps is None else ps
            vs = vels if vs is None else vs
            if keep is not None:
                keep_idx = [i for i, la in enumerate(spec.layers)
                            if keep(la)]
                remap = {old: new for new, old in enumerate(keep_idx)}
                kept_layers = []
                for old in keep_idx:
                    la = spec.layers[old]
                    cfg = la.cfg
                    if "tie" in cfg:
                        # deconv/depool cross-references are layer
                        # INDICES — remap them past the removed layers
                        if cfg["tie"] not in remap:
                            raise RuntimeError(
                                f"variant removes layer {cfg['tie']} "
                                f"that layer {old} ties to")
                        cfg["tie"] = remap[cfg["tie"]]
                        la = dataclasses.replace(
                            la, config=tuple(sorted(cfg.items())))
                    kept_layers.append(la)
                spec = dataclasses.replace(spec,
                                           layers=tuple(kept_layers))
                ps = [ps[i] for i in keep_idx]
                vs = [vs[i] for i in keep_idx]
            cp = jax.tree_util.tree_map(np.array, (ps, vs))
            tr = FusedTrainer(spec=spec, params=cp[0], vels=cp[1])
            for _ in range(getattr(args, "warm", 2)):
                tr.train_epoch(data, target, idx, batch, sync=True)
            t0 = time.perf_counter()
            last = None
            for _ in range(args.epochs):
                last = tr.train_epoch(data, target, idx, batch,
                                      sync=False)
            np.asarray(last["loss"])
            dt = time.perf_counter() - t0
            return dt / max(1, args.epochs * (n // batch)) * 1e3

        # the same model with the LRN+pool merge disabled (split layers)
        # — the A/B for the fused-pair kernel (ops/lrn_pool.py); its own
        # params/vels: the split spec has more layer rows.  The ambient
        # default is fused2 since round 5, so "full" IS the fused2 row
        # and the A/B variant is the phase-1 downgrade.
        os.environ["ZNICZ_TPU_LRN_POOL"] = "split"
        try:
            split_spec, split_params, split_vels = fused.extract_model(wf)
            os.environ["ZNICZ_TPU_LRN_POOL"] = "nofold"
            nofold_spec = fused.extract_model(wf)[0]
            os.environ["ZNICZ_TPU_LRN_POOL"] = "fused1"
            fused1_spec = fused.extract_model(wf)[0]
        finally:
            os.environ.pop("ZNICZ_TPU_LRN_POOL", None)

        # only shape-preserving kinds can be ablated (pooling changes
        # every downstream activation shape, so it has no variant);
        # no_lrn strips LRN from the SPLIT spec, where it is standalone
        variants = [
            ("full", None, base_spec, None, None, None),
            ("lrn_pool_fused1", None, fused1_spec, None, None, None),
            ("lrn_pool_nofold", None, nofold_spec, None, None, None),
            ("lrn_pool_split", None, split_spec, split_params,
             split_vels, None),
            ("no_lrn", lambda la: la.kind != "lrn", split_spec,
             split_params, split_vels, None),
            ("no_dropout", lambda la: la.kind != "dropout", base_spec,
             None, None, None),
            ("storage_bf16", None,
             dataclasses.replace(base_spec, storage_dtype="bfloat16"),
             None, None, None),
            # conv1 space-to-depth (round 4): same spec, env-routed in
            # conv2d at trace time — each row's fresh FusedTrainer
            # re-traces, so the env is honored per row
            ("conv1_s2d", None, base_spec, None, None,
             ("ZNICZ_TPU_CONV1", "s2d")),
        ]
        rows = {}
        for name, keep, spec, ps, vs, env in variants:
            if env is not None:
                os.environ[env[0]] = env[1]
            try:
                rows[name] = round(time_spec(spec, keep, ps, vs), 2)
            except Exception as e:   # a variant may be unbuildable
                rows[name] = f"error: {e}"[:120]
            finally:
                if env is not None:
                    os.environ.pop(env[0], None)
            print(f"  {name:14s} {rows[name]} ms/step",
                  file=sys.stderr)
        result["value"] = rows.get("full")
        result["rows"] = rows
    except Exception as e:
        result.setdefault("error", "")
        result["error"] = (result["error"]
                           + f" ablate failed: {e!r}").strip()[:600]
    finally:
        for var, val in saved_env.items():
            if val is not None:
                os.environ[var] = val
    return _emit(result)


def _time_thunk(thunk, iters=20):
    from znicz_tpu.ops import tuning
    if tuning.interpret_mode():
        iters = 2                   # interpret mode: only timing shape
    import jax
    out = thunk()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = thunk()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e6    # µs


def bench_kernels(args) -> int:
    import jax

    result = {"metric": "pallas_kernel_validation", "value": None,
              "unit": "kernels_passed", "vs_baseline": None}
    platform = _bring_up(args, result, reduce_on_cpu=False)
    if platform is None:
        return _emit(result)
    _record_run_config(args, result)
    from znicz_tpu.ops import tuning
    if not tuning.use_pallas():
        result["error"] = (f"platform {platform!r}: Pallas disabled and "
                           f"not in interpret mode")
        return _emit(result)
    rows, passed = [], 0
    for name, pallas_t, xla_t, mode in _kernel_cases():
        row = {"kernel": name}
        try:
            got = [np.asarray(g)
                   for g in jax.tree_util.tree_leaves(pallas_t())]
            ref = [np.asarray(r)
                   for r in jax.tree_util.tree_leaves(xla_t())]
            ok = len(got) == len(ref)
            err = 0.0
            for g, r in zip(got, ref):       # every output must match
                if mode == "exact":
                    ok = ok and bool(np.array_equal(g, r))
                else:
                    ok = ok and bool(np.allclose(g, r, rtol=2e-3,
                                                 atol=2e-3))
                err = max(err, float(np.max(np.abs(
                    g.astype(np.float64) - r.astype(np.float64)))))
            row["pass"] = ok
            row["max_abs_err"] = err
            row["pallas_us"] = round(_time_thunk(pallas_t), 1)
            row["xla_us"] = round(_time_thunk(xla_t), 1)
            passed += ok
        except Exception as e:
            row["pass"] = False
            row["error"] = str(e)[:300]
        rows.append(row)
        print(f"  {name:16s} pass={row.get('pass')} "
              f"pallas={row.get('pallas_us', '-')}us "
              f"xla={row.get('xla_us', '-')}us "
              f"err={row.get('max_abs_err', row.get('error', '-'))}",
              file=sys.stderr)
    result["value"] = passed
    result["total"] = len(rows)
    result["rows"] = rows
    return _emit(result)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == "serve":
        # `bench.py serve ...` reads like the serve CLI it drives;
        # normalize to the flag form argparse speaks
        argv = ["--serve", *argv[1:]]
    p = argparse.ArgumentParser()
    p.add_argument("--config", default="alexnet")
    p.add_argument("--minibatch", type=int, default=128)
    p.add_argument("--n-train", type=int, dest="n_train", default=512)
    p.add_argument("--epochs", type=int, default=4)
    p.add_argument("--ticks", type=int, default=4)
    p.add_argument("--backend-wait", type=float, default=420.0)
    p.add_argument("--dtype", default=None,
                   choices=(None, "float32", "bfloat16"),
                   help="compute dtype for the fused path's MXU operands"
                        " (params/accumulation stay f32)")
    p.add_argument("--storage", default=None,
                   choices=(None, "float32", "bfloat16"),
                   help="dtype activations are stored in between layers"
                        " (bfloat16 halves activation HBM traffic;"
                        " params/grads/loss stay f32)")
    p.add_argument("--kernels", action="store_true")
    p.add_argument("--loader", action="store_true",
                   help="disk→batch loader throughput, no device in "
                        "the loop (combine with --augment for the "
                        "decode→crop variant)")
    p.add_argument("--ablate", action="store_true",
                   help="time the fused step with layer kinds removed"
                        " (the 'where the time goes' table)")
    p.add_argument("--stream", action="store_true",
                   help="also measure the disk-backed streaming path")
    p.add_argument("--augment", action="store_true",
                   help="also measure with on-device RandomCropFlip in"
                        " the scan (alexnet: decode+29 -> crop)")
    p.add_argument("--mesh", default=None, metavar="DP[,TP]",
                   help="lay the fused step out over a (data, model) "
                        "device mesh, e.g. '4,2'; the row stamps the "
                        "scheme as sharding='dpxtp' so decide_levers "
                        "pairs like-for-like (omitted = '1x1')")
    p.add_argument("--serve", action="store_true",
                   help="request-path bench: boot a real `serve` "
                        "subprocess, drive closed-loop HTTP traffic, "
                        "and stamp a rev-stamped transcript row with "
                        "req/s/core + p50/p99 + device-ms/request "
                        "(`bench.py serve` works too; ROADMAP "
                        "request-path speed arc)")
    p.add_argument("--serve-model", default=None, metavar="PATH",
                   help="serve bench: .znn to serve (default: the "
                        "tiny built-in demo model)")
    p.add_argument("--serve-width", type=int, default=4,
                   help="serve bench: flat input feature count of "
                        "--serve-model (ignored for the demo model)")
    p.add_argument("--serve-clients", type=int, default=4,
                   help="serve bench: concurrent closed-loop client "
                        "threads")
    p.add_argument("--serve-rows", type=int, default=1,
                   help="serve bench: rows per /predict request")
    p.add_argument("--serve-duration-s", type=float, default=5.0,
                   help="serve bench: measured traffic window")
    p.add_argument("--payload", default="json",
                   choices=("json", "binary"),
                   help="serve bench: wire format of the driven "
                        "traffic — json (the historical contract) or "
                        "binary (application/x-znicz-tensor, the "
                        "zero-copy path); stamped into the transcript "
                        "row so trajectories pair like-for-like")
    p.add_argument("--fleet", type=int, default=0, metavar="N",
                   help="serve bench: boot N serve backends behind a "
                        "real `route` process and drive the traffic "
                        "through the ROUTER — the row stamps "
                        "sharding='fleetxN' (device-ms summed across "
                        "backends), so the fabric's forwarding "
                        "overhead vs the single-process rows is a "
                        "measured trajectory (docs/fleet.md)")
    p.add_argument("--router-url", action="append", default=[],
                   metavar="URL",
                   help="serve bench: drive EXISTING router(s) "
                        "instead of booting a fleet — repeatable to "
                        "name an HA pair (primary + hot standbys): "
                        "clients fail over to the next url on "
                        "transport error, a 503 + Retry-After "
                        "takeover refusal stays an answer; the row "
                        "stamps sharding='externalxN' and device-ms "
                        "0 (the backends aren't ours to scrape) "
                        "(docs/fleet.md 'Router high availability')")
    p.add_argument("--placement", action="store_true",
                   help="serve bench with --fleet N: backends serve "
                        "the demo ZOO and the router runs "
                        "--placement 1 — traffic cycles the tenants, "
                        "the row stamps sharding='fleetxN+place' plus "
                        "fleet_resident_bytes/zoo_total_bytes, so the "
                        "footprint win of placement over N-clones is "
                        "measured, not asserted (docs/fleet.md)")
    p.add_argument("--trace-breakdown", action="store_true",
                   help="serve bench: stamp a traceparent on every "
                        "driven request and report the per-stage "
                        "p50/p99 latency decomposition (router-"
                        "assembled in --fleet mode, assembled locally "
                        "from the server's in-band span summary "
                        "otherwise), plus the stage-sum vs e2e "
                        "honesty ratio (docs/observability.md)")
    p.add_argument("--repeat-fraction", type=float, default=0.0,
                   help="serve bench: fraction [0,1] of requests "
                        "reusing ONE fixed input (the rest are "
                        "unique per request) — drives the response-"
                        "memoization hit rate; > 0 boots the server "
                        "with --memoize, and the fraction is stamped "
                        "into the transcript row")
    args = p.parse_args(argv)
    if not 0.0 <= args.repeat_fraction <= 1.0:
        p.error(f"--repeat-fraction must be in [0, 1], "
                f"got {args.repeat_fraction}")
    try:
        if args.serve:
            return bench_serve(args)
        if args.kernels:
            return bench_kernels(args)
        if args.loader:
            return bench_loader(args)
        if args.ablate:
            return bench_ablate(args)
        return bench_training(args)
    except SystemExit:
        raise
    except BaseException as e:          # last-ditch: line must parse
        return _emit({"metric": "bench_error", "value": None,
                      "unit": "images/sec", "vs_baseline": None,
                      "error": repr(e)[:600]})


if __name__ == "__main__":
    sys.exit(main())
