#!/usr/bin/env python
"""Benchmark harness — prints ONE JSON line.

Metric (BASELINE.md plan, step 1–2): MNIST MLP training throughput
(images/sec) through the fused TPU path, with the numpy golden path on this
host as the stand-in reference baseline (the reference's own numbers are
unrecoverable — BASELINE.md provenance note).  ``vs_baseline`` is the
speedup of the TPU path over that baseline."""

import json
import sys
import time

import numpy as np


def measure_numpy_baseline(epochs: int = 2) -> float:
    """Images/sec of the unit-graph numpy_run path (reference-equivalent
    CPU execution model: per-unit Python dispatch + numpy math)."""
    from znicz_tpu import prng
    prng.seed_all(1234)
    from znicz_tpu.backends import Device
    from znicz_tpu.config import root
    from znicz_tpu.models import mnist

    root.mnist.synthetic.update({"n_train": 5000, "n_valid": 1000,
                                 "n_test": 1000})
    wf = mnist.MnistWorkflow()
    wf.decision.max_epochs = epochs
    wf.initialize(device=Device.create("numpy"))
    t0 = time.perf_counter()
    wf.run()
    dt = time.perf_counter() - t0
    # each epoch processes every class (train fwd+bwd, valid/test fwd)
    images = wf.loader.total_samples * epochs
    return images / dt


def measure_fused_tpu(epochs: int = 20) -> float:
    from znicz_tpu import prng
    prng.seed_all(1234)
    from znicz_tpu.backends import Device
    from znicz_tpu.config import root
    from znicz_tpu.models import mnist
    from znicz_tpu.parallel import FusedTrainer

    root.mnist.synthetic.update({"n_train": 5000, "n_valid": 1000,
                                 "n_test": 1000})
    wf = mnist.MnistWorkflow()
    wf.initialize(device=Device.create("xla"))
    tr = FusedTrainer(wf)
    ld = wf.loader
    data, target = ld.original_data.devmem, ld.original_labels.devmem
    n0, n1, n2 = ld.class_lengths
    test_idx = np.arange(0, n0)
    valid_idx = np.arange(n0, n0 + n1)
    train_idx = np.arange(n0 + n1, n0 + n1 + n2)
    batch = ld.max_minibatch_size

    def one_epoch():
        """Same per-epoch work as the baseline: train fwd+bwd over the
        train set, eval fwd over valid+test."""
        m = tr.train_epoch(data, target, train_idx, batch, sync=False)
        tr.eval_epoch(data, target, valid_idx, batch, sync=False)
        tr.eval_epoch(data, target, test_idx, batch, sync=False)
        return m

    one_epoch()                                   # compile+warm
    t0 = time.perf_counter()
    last = None
    for _ in range(epochs):
        last = one_epoch()
    np.asarray(last["loss"])          # one sync at the end
    dt = time.perf_counter() - t0
    return epochs * (n0 + n1 + n2) / dt


def main() -> None:
    fused = measure_fused_tpu()
    baseline = measure_numpy_baseline()
    print(json.dumps({
        "metric": "mnist_mlp_train_images_per_sec",
        "value": round(fused, 1),
        "unit": "images/sec",
        "vs_baseline": round(fused / baseline, 2),
    }))


if __name__ == "__main__":
    sys.exit(main())
